"""The telemetry time-series plane: windowed quantiles over snapshot
rings, scrape-diffing the native proxy, and the ``/debug/telemetry``
endpoints on both planes.

The delta-bucket math is the load-bearing piece: ``window_quantile`` must
answer from ONLY the samples observed inside the window (the delta of the
cumulative buckets between two ring snapshots), never the lifetime
distribution — a week-old process's history must not drown the last 30
seconds. Covered: delta-vs-lifetime under concurrent observe, ring
eviction at the cap, empty-window and counter-reset (process restart)
behavior, and a native-scrape diff round-trip.
"""

from __future__ import annotations

import http.client
import io
import json
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

from demodel_tpu.utils import metrics as m
from demodel_tpu.utils import statusz, trace
from demodel_tpu.utils.faults import PeerHealth

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _fresh_state():
    trace.reset()
    m.HUB.reset()
    PeerHealth.reset_shared()
    yield
    trace.reset()
    m.HUB.reset()
    PeerHealth.reset_shared()


def _clocked_telemetry(cap=8, min_gap=0.0):
    clock = {"t": 0.0}
    tel = m.Telemetry(m._hub_source(m.HUB), cap=cap, min_gap_s=min_gap,
                      clock=lambda: clock["t"])
    return tel, clock


# ------------------------------------------------------ delta-bucket math


def test_window_quantile_is_delta_not_lifetime():
    """1000 historic fast samples, 10 recent slow ones: the lifetime p50
    stays fast, the window p50 must report the recent slowness."""
    tel, clock = _clocked_telemetry()
    for _ in range(1000):
        m.HUB.observe("stage", 0.003)   # bucket le=0.0032
    tel.sample()
    clock["t"] = 30.0
    for _ in range(10):
        m.HUB.observe("stage", 0.05)    # bucket le=0.0512
    tel.sample()
    assert m.HUB.get_histogram("stage").quantile(0.5) == \
        pytest.approx(0.0032)
    assert tel.window_quantile("stage", 0.5, 30) == pytest.approx(0.0512)
    assert tel.window_quantile("stage", 0.99, 30) == pytest.approx(0.0512)
    d = tel.window_delta("stage", 30)
    assert d["count"] == 10 and d["elapsed_s"] == pytest.approx(30.0)


def test_windowed_quantiles_under_concurrent_observe():
    """Writers hammering the hub while a sampler ticks: every window
    delta must stay non-negative and internally consistent (the hub
    snapshot is taken under its lock, so a ring entry is a coherent
    point-in-time copy, never a torn read)."""
    tel, clock = _clocked_telemetry(cap=64)
    stop = threading.Event()

    def writer():
        while not stop.is_set():
            m.HUB.observe("conc", 0.004)
            m.HUB.inc("conc_total")

    threads = [threading.Thread(target=writer) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        for i in range(30):
            clock["t"] = float(i)
            tel.sample()
            time.sleep(0.002)
    finally:
        stop.set()
        for t in threads:
            t.join()
    for w in (5, 30):
        d = tel.window_delta("conc", w)
        assert d is not None
        assert all(c >= 0 for c in d["counts"])
        assert d["count"] == sum(d["counts"])
        assert tel.rate("conc_total", w) >= 0
    assert tel.window_quantile("conc", 0.99, 30) == pytest.approx(0.0064)


def test_ring_eviction_at_cap():
    tel, clock = _clocked_telemetry(cap=4)
    for i in range(10):
        clock["t"] = float(i)
        m.HUB.inc("evict_total")
        tel.sample()
    assert len(tel) == 4
    assert tel.samples_taken == 10
    # the window can only reach back to the oldest SURVIVING snapshot:
    # 4 ticks × 1 counter-inc each → a 100s window sees 3 increments
    assert tel.rate("evict_total", 100) == pytest.approx(3 / 3.0)


def test_per_label_windowed_views():
    """The per-peer attribution surface: label kwargs on the windowed
    views select ONE labeled series, and label_rates fans a family out
    into every live series with its labels intact."""
    tel, clock = _clocked_telemetry()
    m.HUB.inc(m.labeled("peer_retries_total", peer="tpu-a"), 3)
    m.HUB.inc(m.labeled("peer_retries_total", peer="tpu-b"), 1)
    m.HUB.inc("pulls_total", 5)
    tel.sample()
    clock["t"] = 10.0
    m.HUB.inc(m.labeled("peer_retries_total", peer="tpu-a"), 7)
    m.HUB.observe(m.labeled("stage_duration_seconds", span="place"), 0.05)
    tel.sample()
    assert tel.rate("peer_retries_total", 10, peer="tpu-a") == \
        pytest.approx(0.7)
    assert tel.rate("peer_retries_total", 10, peer="tpu-b") == 0.0
    assert tel.window_quantile("stage_duration_seconds", 0.99, 10,
                               span="place") == pytest.approx(0.0512)
    rates = tel.label_rates("peer_retries_total", 10)
    assert rates == {'peer_retries_total{peer="tpu-a"}':
                     pytest.approx(0.7)}
    # the hub facade forwards the same kwargs
    assert m.HUB.rate is not None
    # the windowed reads above freshen (min_gap 0), appending extra
    # same-valued snapshots — assert the endpoints, not the count
    series = tel.series("peer_retries_total", peer="tpu-a")
    assert series[0]["value"] == 3 and series[-1]["value"] == 10


def test_parse_labels_round_trip():
    name = m.labeled("peer_retries_total", peer="tpu-a",
                     note='quo"te\\back')
    base, labels = m.parse_labels(name)
    assert base == "peer_retries_total"
    assert labels == {"peer": "tpu-a", "note": 'quo"te\\back'}
    assert m.parse_labels("pulls_total") == ("pulls_total", {})


def test_summary_carries_per_series_rates_with_labels():
    tel, clock = _clocked_telemetry()
    m.HUB.inc(m.labeled("peer_retries_total", peer="tpu-a"))
    tel.sample()
    clock["t"] = 10.0
    m.HUB.inc(m.labeled("peer_retries_total", peer="tpu-a"), 9)
    tel.sample()
    rates = tel.summary()["rates"]
    key = 'peer_retries_total{peer="tpu-a"}'
    assert key in rates and rates[key]["30"] > 0


def test_empty_window_behavior():
    # high min-gap: freshen() may take the FIRST snapshot (empty ring)
    # but never piles extras onto the injected clock
    tel, clock = _clocked_telemetry(min_gap=999.0)
    # no window at all: one snapshot max, nothing to diff
    assert tel.rate("nothing_total", 30) == 0.0
    assert tel.window_quantile("nothing", 0.99, 30) == 0.0
    assert tel.window_delta("nothing", 30) is None
    assert tel.series("nothing") == []
    # two snapshots with NO new samples between them: empty window, 0.0
    m.HUB.observe("quiet", 0.01)
    clock["t"] = 10.0
    tel.sample()
    clock["t"] = 20.0
    tel.sample()
    d = tel.window_delta("quiet", 10)
    assert d["count"] == 0 and tel.window_quantile("quiet", 0.99, 10) == 0.0
    # a window reaching back BEFORE the family existed counts everything
    # (an absent baseline is an empty baseline)
    assert tel.window_quantile("quiet", 0.99, 30) == pytest.approx(0.0128)


def test_counter_reset_is_rate_from_zero():
    """A restarted process re-registers counters near zero: the window
    must not report a huge negative (or wrapped) rate — the Prometheus
    convention is rate-from-zero."""
    feed = {"counters": {"x_total": 1000.0}, "gauges": {}, "hists": {}}
    clock = {"t": 0.0}
    tel = m.Telemetry(lambda: {k: dict(v) for k, v in feed.items()},
                      cap=8, min_gap_s=0.0, clock=lambda: clock["t"])
    tel.sample()
    clock["t"] = 10.0
    feed["counters"] = {"x_total": 40.0}  # restarted: 1000 → 40
    tel.sample()
    assert tel.rate("x_total", 10) == pytest.approx(4.0)


def test_histogram_reset_zeroes_the_baseline():
    h1 = {"le": list(m.BUCKET_BOUNDS),
          "counts": [50] + [0] * len(m.BUCKET_BOUNDS), "sum": 1.0}
    h2 = {"le": list(m.BUCKET_BOUNDS),
          "counts": [3] + [0] * len(m.BUCKET_BOUNDS), "sum": 0.01}
    feed = {"counters": {}, "gauges": {}, "hists": {"h": h1}}
    clock = {"t": 0.0}
    tel = m.Telemetry(lambda: json.loads(json.dumps(feed)), cap=8,
                      min_gap_s=0.0, clock=lambda: clock["t"])
    tel.sample()
    clock["t"] = 30.0
    feed["hists"]["h"] = h2
    tel.sample()
    d = tel.window_delta("h", 30)
    assert d["count"] == 3, "a shrunken bucket means reset → zero baseline"


def test_failing_source_degrades_not_crashes():
    calls = {"n": 0}

    def source():
        calls["n"] += 1
        raise RuntimeError("proxy stopped")

    tel = m.Telemetry(source, cap=8, min_gap_s=0.0)
    assert tel.sample() is False
    assert tel.samples_failed == 1 and len(tel) == 0
    assert tel.rate("x", 30) == 0.0  # freshen retries, still no crash


# ------------------------------------------------- native scrape diffing


class _FakeProxy:
    """ProxyServer-shaped: .metrics() returns the native JSON shape."""

    def __init__(self):
        self._h = object()  # "running" marker native_source checks
        self.requests = 0
        self.counts = [0] * (m.Histogram().bounds.__len__() + 1)
        self.sum = 0.0

    def observe(self, sec):
        from bisect import bisect_left

        self.counts[bisect_left(m.BUCKET_BOUNDS, sec)] += 1
        self.sum += sec
        self.requests += 1

    def metrics(self):
        return {
            "requests": self.requests,
            "sessions_active": 2,
            "hist": {
                "serve_request_seconds": {
                    "le": list(m.BUCKET_BOUNDS),
                    "routes": {
                        "peer_object": {"counts": list(self.counts),
                                        "sum": self.sum,
                                        "count": sum(self.counts)},
                    },
                },
            },
        }


def test_native_scrape_diff_round_trip():
    """The Python-side mirror of the native plane: successive scrapes
    diffed into the same windowed views the hub gets — counter rates,
    gauge last-value, and delta-bucket quantiles per route."""
    proxy = _FakeProxy()
    clock = {"t": 0.0}
    tel = m.Telemetry(m.native_source(proxy), cap=16, min_gap_s=0.0,
                      clock=lambda: clock["t"])
    proxy.observe(0.003)
    proxy.requests += 10
    tel.sample()
    clock["t"] = 30.0
    for _ in range(5):
        proxy.observe(0.1)
    proxy.requests += 30
    tel.sample()
    name = m.labeled("serve_request_seconds", route="peer_object")
    assert tel.window_quantile(name, 0.99, 30) == pytest.approx(0.1024)
    assert tel.rate("requests", 30) == pytest.approx(35 / 30.0)
    d = tel.window_delta(name, 30)
    assert d["count"] == 5 and d["sum"] == pytest.approx(0.5)
    # gauges pass through as last-value
    assert tel.summary()["gauges"]["sessions_active"] == 2
    # a stopped proxy (handle freed) degrades to skipped samples
    proxy._h = None
    assert tel.sample() is False
    assert m.native_telemetry(proxy) is m.native_telemetry(proxy)


# --------------------------------------------------- /debug/telemetry


def _get_json(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request("GET", path, headers={"Connection": "close"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())
    finally:
        conn.close()


def test_restore_server_telemetry_endpoint(tmp_path):
    from demodel_tpu.restore.server import RestoreRegistry, RestoreServer
    from demodel_tpu.store import Store

    store = Store(tmp_path / "s")
    try:
        with RestoreServer(RestoreRegistry(store),
                           host="127.0.0.1") as srv:
            with trace.span("window-read"):
                pass
            m.HUB.telemetry().sample()
            time.sleep(0.3)
            with trace.span("window-read"):
                time.sleep(0.001)
            status, doc = _get_json(srv.port, "/debug/telemetry")
            assert status == 200
            assert doc["telemetry"] == 1 and doc["server"] == "restore"
            assert doc["windows"]["windows_s"] == [30, 300]
            fam = doc["windows"]["hist"][
                'stage_duration_seconds{span="window-read"}']
            assert fam["30"]["count"] >= 1 and fam["30"]["p99"] > 0
            # the statusz document carries the compact telemetry slice
            # and the effective-config section, and both pass the gate
            status, sdoc = _get_json(srv.port, "/debug/statusz")
            assert sdoc["telemetry"]["windows_s"] == [30, 300]
            assert sdoc["config"]["DEMODEL_PEER_STREAMS"]["source"] in (
                "env", "default")
            for url_path in ("/debug/statusz", "/debug/telemetry"):
                proc = subprocess.run(
                    [sys.executable, "tools/statusz.py",
                     f"http://127.0.0.1:{srv.port}{url_path}",
                     "--validate"],
                    cwd=REPO, capture_output=True, text=True, timeout=60)
                assert proc.returncode == 0, (url_path, proc.stderr)
    finally:
        store.close()


def test_native_proxy_telemetry_endpoint(tmp_path, monkeypatch):
    monkeypatch.setenv("DEMODEL_TELEMETRY_MIN_GAP_MS", "50")
    from demodel_tpu.config import ProxyConfig
    from demodel_tpu.proxy import ProxyServer

    cfg = ProxyConfig(host="127.0.0.1", port=0, mitm_hosts=[],
                      no_mitm=True, cache_dir=tmp_path / "c",
                      data_dir=tmp_path / "d")
    node = ProxyServer(cfg, verbose=False).start()
    try:
        status, first = _get_json(node.port, "/debug/telemetry")
        assert status == 200 and first["telemetry"] == 1
        assert first["server"] == "demodel-native-proxy"
        assert set(first["windows"]) == {"30", "300"}
        for _ in range(5):
            _get_json(node.port, "/healthz")
        time.sleep(0.1)
        _status, doc = _get_json(node.port, "/debug/telemetry")
        assert doc["snapshots"] >= 2
        served = doc["windows"]["30"]["serve_request_seconds"]
        assert served["healthz"]["count"] >= 5
        assert served["healthz"]["p99"] > 0
        assert served["healthz"]["rate"] > 0
        # schema gate accepts the native document too
        proc = subprocess.run(
            [sys.executable, "tools/statusz.py",
             f"http://127.0.0.1:{node.port}/debug/telemetry", "--validate"],
            cwd=REPO, capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stderr

        # the Python mirror over the SAME proxy: both sides serve a
        # windowed serve-leg p99 (the acceptance criterion's two planes)
        tel = m.native_telemetry(node)
        tel.sample()
        for _ in range(5):
            _get_json(node.port, "/healthz")
        time.sleep(0.05)
        tel.sample()
        name = m.labeled("serve_request_seconds", route="healthz")
        assert tel.window_quantile(name, 0.99, 30) > 0
    finally:
        node.stop()


def test_statusz_config_reports_env_and_tuner_sources(monkeypatch):
    monkeypatch.setenv("DEMODEL_SWARM_CHUNK_MB", "4")
    cfg = statusz.effective_config()
    assert cfg["DEMODEL_SWARM_CHUNK_MB"] == {"value": 4, "source": "env"}
    assert cfg["DEMODEL_RETRY_MAX"]["source"] == "default"
    from demodel_tpu.sink.tuner import PullTuner

    tuner = PullTuner(prefetch_depth=2, tick_s=5, window_s=5)
    tuner.start()
    try:
        cfg = statusz.effective_config()
        assert cfg["DEMODEL_PEER_STREAMS"]["source"] == "tuner"
        assert cfg["DEMODEL_PEER_STREAMS"]["value"] == tuner.streams
        assert cfg["DEMODEL_PULL_WINDOW_MB"] == {
            "value": tuner.window_mb, "source": "tuner"}
    finally:
        tuner.stop()
    assert statusz.effective_config()["DEMODEL_PEER_STREAMS"]["source"] \
        != "tuner"


def test_statusz_config_scrape_stays_dep_light():
    """The effective-config section must resolve every knob default
    WITHOUT importing jax/numpy or the sink/parallel planes — importing
    parallel.peer, parallel.placement, or sink.tuner runs their
    packages' __init__ and drags jax into a dep-light scrape (the knob
    resolvers live in utils.env for exactly this reason)."""
    code = (
        "import sys\n"
        "from demodel_tpu.utils import statusz\n"
        "doc = statusz.snapshot()\n"
        "assert doc['config']['DEMODEL_TUNER']['value'] is True\n"
        "assert doc['config']['DEMODEL_SWARM_FILL_TIMEOUT']['value'] == 60\n"
        "for mod in ('jax', 'numpy', 'demodel_tpu.sink.tuner',\n"
        "            'demodel_tpu.parallel.peer',\n"
        "            'demodel_tpu.parallel.placement'):\n"
        "    assert mod not in sys.modules, mod + ' leaked'\n")
    proc = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr


def test_fleet_watch_emits_jsonl_series(tmp_path):
    from demodel_tpu.restore.server import RestoreRegistry, RestoreServer
    from demodel_tpu.store import Store
    from tools.statusz import watch_fleet

    store = Store(tmp_path / "s")
    try:
        with RestoreServer(RestoreRegistry(store),
                           host="127.0.0.1") as srv:
            with trace.span("window-read"):
                pass
            out = io.StringIO()
            rc = watch_fleet(
                [f"127.0.0.1:{srv.port}", "127.0.0.1:9"],
                interval_s=0.3, samples=2, out=out)
            assert rc == 0
            lines = [json.loads(x) for x in
                     out.getvalue().strip().splitlines()]
            assert len(lines) == 2
            for tick in lines:
                assert tick["metric"] == "telemetry_fleet"
                (host,) = tick["hosts"]
                assert host["server"] == "restore"
                (down,) = tick["unreachable"]
                assert down["host"] == "127.0.0.1:9"
            # the second tick has a window (the watch itself drove the
            # sampling cadence)
            p99s = lines[1]["hosts"][0]["p99_30s"]
            assert 'stage_duration_seconds{span="window-read"}' in p99s
    finally:
        store.close()


def test_hub_reset_clears_the_ring():
    m.HUB.inc("reset_total")
    m.HUB.telemetry().sample()
    assert len(m.HUB.telemetry()) == 1
    m.HUB.reset()
    assert len(m.HUB.telemetry()) == 0


def test_concurrent_freshens_take_one_sample():
    """Regression (PR 10, atomic-snapshot finding): freshen()'s staleness
    check and its decision to sample used to live under two separate
    lock holds — two consumers polling one stale ring would BOTH pass
    the gap test and land back-to-back snapshots, violating the min-gap
    contract. Deterministic: the first freshen blocks inside the scrape,
    the second must return without sampling."""
    entered = threading.Event()
    release = threading.Event()

    def blocking_source():
        entered.set()
        assert release.wait(5), "test wiring: scrape never released"
        return {"counters": {"c": 1.0}, "gauges": {}, "hists": {}}

    tel = m.Telemetry(blocking_source, cap=8, min_gap_s=30.0,
                      clock=lambda: 100.0)
    t1 = threading.Thread(target=tel.freshen, daemon=True)
    t1.start()
    assert entered.wait(5)
    # ring still empty and stale — the OLD check-then-act would sample
    # again here; the claim flag must make this a no-op
    before = tel.samples_taken
    tel.freshen()
    assert tel.samples_taken == before, \
        "second freshen sampled while the first was mid-scrape"
    release.set()
    t1.join(timeout=5)
    assert tel.samples_taken == 1
    assert len(tel) == 1
    # and the claim is RELEASED: a later stale poll samples again
    tel.min_gap_s = 0.0
    tel.freshen()
    assert tel.samples_taken == 2


def test_freshen_claim_survives_a_raising_source():
    """A scrape that raises must release the freshen claim — otherwise
    one dead source wedges the ring forever."""
    calls = {"n": 0}

    def source():
        calls["n"] += 1
        raise RuntimeError("source down")

    tel = m.Telemetry(source, cap=8, min_gap_s=0.0, clock=lambda: 100.0)
    tel.freshen()
    assert tel.samples_failed == 1
    tel.freshen()  # the claim from the failed attempt must not linger
    assert calls["n"] == 2 and tel.samples_failed == 2
