"""The tier plane: single-flight admission + mmap hot tier contracts.

What is proven here, against REAL wire faults (tests/chaoshttp.py) where
the contract is about failure:

- a thundering herd of cold readers costs exactly ONE upstream fetch and
  every member gets byte-exact results off the landing stream;
- a leader dying mid-stream (RST) hands the flight to a waiter, which
  RESUMES the partial with a ranged fetch — the origin never re-serves
  the landed prefix, and the cohort still lands byte-exact;
- a digest mismatch (corrupt origin bytes) fails the whole cohort
  without committing the bytes and without poisoning the key — the next
  read starts a fresh flight and succeeds;
- promotion into the mmap hot tier is digest-verified (bytes that no
  longer match their content address are refused, never served from
  RAM) and the tier stays inside its byte budget by LRU demotion.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest
import requests

from demodel_tpu import tier
from demodel_tpu.store import Store
from demodel_tpu.utils import metrics as m
from demodel_tpu.utils.faults import DigestMismatch

from .chaoshttp import ChaosPeer, FaultPlan, FaultSpec

KEY = "tierblob00000001"


@pytest.fixture(autouse=True)
def _fresh_metrics():
    m.HUB.reset()
    yield


@pytest.fixture()
def store(tmp_path):
    s = Store(tmp_path / "tier-store")
    yield s
    s.close()


def _blob(mb: int = 4, seed: int = 7) -> bytes:
    # deterministic, compressible-resistant body
    one = bytes((i * 31 + seed) & 0xFF for i in range(1 << 20))
    return one * mb


class _RangeOrigin:
    """A minimal Range-capable blob server — the REAL upstream behind
    the chaos shim (the shim forwards Range headers verbatim)."""

    def __init__(self, blobs: dict[str, bytes]):
        outer_blobs = dict(blobs)

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # noqa: ARG002
                pass

            def do_GET(self):
                body = outer_blobs.get(self.path)
                if body is None:
                    payload = b'{"error":"not found"}'
                    self.send_response(404)
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                    return
                rng = self.headers.get("Range", "")
                start = 0
                if rng.startswith("bytes="):
                    start = int(rng[6:].split("-", 1)[0] or 0)
                part = body[start:]
                self.send_response(206 if start else 200)
                if start:
                    self.send_header(
                        "Content-Range",
                        f"bytes {start}-{len(body) - 1}/{len(body)}")
                self.send_header("Accept-Ranges", "bytes")
                self.send_header("Content-Length", str(len(part)))
                self.end_headers()
                self.wfile.write(part)

        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._srv.daemon_threads = True
        self.url = f"http://127.0.0.1:{self._srv.server_address[1]}"
        threading.Thread(target=self._srv.serve_forever, daemon=True).start()

    def close(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()

    def __enter__(self) -> "_RangeOrigin":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _http_fetch(base_url: str):
    """A ``fetch(key, offset)`` upstream doing ranged GETs — what the
    delivery plane's origin/peer fetchers look like to the tier."""

    def fetch(key: str, offset: int):
        headers = {"Connection": "close"}
        if offset:
            headers["Range"] = f"bytes={offset}-"
        r = requests.get(f"{base_url}/{key}", headers=headers,
                         stream=True, timeout=30)
        if r.status_code not in (200, 206):
            raise OSError(f"origin status {r.status_code}")
        if offset and r.status_code != 206:
            raise OSError("origin ignored Range")
        yield from r.iter_content(256 << 10)

    return fetch


def _herd(ts: tier.TieredStore, fetch, n: int, digest: str | None = None,
          timeout: float = 60.0):
    """Barrier-release ``n`` concurrent reads; returns (results, errors)
    index-aligned."""
    gate = threading.Barrier(n)
    results: list = [None] * n
    errors: list = [None] * n

    def client(i: int) -> None:
        try:
            gate.wait(timeout=30)
            results[i] = ts.read(KEY, fetch=fetch, expected_digest=digest,
                                 timeout=timeout)
        except BaseException as e:  # noqa: BLE001 — asserted by callers
            errors[i] = e

    threads = [threading.Thread(target=client, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results, errors


# ---------------------------------------------------------------- herd


def test_herd_one_fetch_bytes_exact(store):
    """N cold readers, one counting upstream: exactly one fetch, every
    reader byte-exact (waiters ride the landing stream), and the object
    lands hot."""
    body = _blob(2)
    calls = []

    def fetch(key, offset):
        calls.append((key, offset))
        for i in range(offset, len(body), 256 << 10):
            yield body[i:i + (256 << 10)]

    ts = tier.TieredStore(store, name="t-herd")
    try:
        results, errors = _herd(ts, fetch, 8)
        assert errors == [None] * 8, errors
        assert all(r == body for r in results)
        assert calls == [(KEY, 0)]
        snap = m.HUB.snapshot()
        assert snap.get("singleflight_leaders_total") == 1
        assert snap.get("singleflight_waiters_total") == 7
        # committed + promoted: the next read is a RAM hit
        assert store.has(KEY)
        assert ts.hot.contains(KEY)
        assert ts.read(KEY) == body
    finally:
        ts.close()


def test_herd_leader_death_waiter_takeover_resumes(store):
    """A truncated stream kills the leader's fetch mid-body: the leader
    gets its own wire error back (retry is the caller's), a waiter takes
    the flight over and RESUMES the partial with a ranged fetch — two
    origin requests total, the second carrying Range from the watermark,
    the landed prefix never crossing the wire twice, and every WAITER
    still lands byte-exact."""
    body = _blob(4)
    cut = 2_000_000
    plan = FaultPlan(
        FaultSpec("truncate", path=KEY, at_byte=cut, min_body=1 << 20),
        seed=3)
    with _RangeOrigin({f"/{KEY}": body}) as origin, \
            ChaosPeer(origin.url, plan) as chaos:
        ts = tier.TieredStore(store, name="t-takeover")
        try:
            results, errors = _herd(ts, _http_fetch(chaos.url), 4,
                                    timeout=30.0)
            assert plan.fired("truncate") == 1
            # exactly one caller — the original leader — surfaces the
            # wire error; the three others ride the handed-off flight
            wire_errs = [e for e in errors if e is not None]
            assert len(wire_errs) == 1, errors
            assert not isinstance(wire_errs[0], DigestMismatch)
            good = [r for r in results if r is not None]
            assert len(good) == 3 and all(r == body for r in good)
            # resume, not redo: the second request is ranged from the
            # watermark, so the landed prefix never crosses the wire twice
            ranged = [rng for _p, rng in chaos.requests_log if rng]
            assert len(chaos.requests_log) == 2, chaos.requests_log
            assert len(ranged) == 1 and ranged[0].startswith("bytes=")
            resume_at = int(ranged[0][6:].rstrip("-"))
            assert 0 < resume_at <= cut
            assert chaos.bytes_served <= len(body) + (cut - resume_at)
            assert m.HUB.snapshot().get("singleflight_handoffs_total") == 1
            assert store.has(KEY)
            assert ts.read(KEY) == body  # and the failed caller's retry
        finally:                         # is now a disk/RAM hit
            ts.close()


def test_digest_mismatch_fails_cohort_without_poisoning(store):
    """Corrupt origin bytes: the digest gate fails the WHOLE cohort, the
    partial is dropped (resuming wrong bytes would re-fail every
    successor), nothing is committed — and the key is not poisoned: the
    next read starts a fresh flight and succeeds."""
    body = _blob(2)
    digest = hashlib.sha256(body).hexdigest()
    plan = FaultPlan(
        FaultSpec("corrupt", path=KEY, at_byte=512_000, min_body=1 << 20),
        seed=5)
    with _RangeOrigin({f"/{KEY}": body}) as origin, \
            ChaosPeer(origin.url, plan) as chaos:
        ts = tier.TieredStore(store, name="t-digest")
        try:
            results, errors = _herd(ts, _http_fetch(chaos.url), 3,
                                    digest=digest, timeout=30.0)
            assert plan.fired("corrupt") == 1
            assert results == [None] * 3
            assert all(isinstance(e, DigestMismatch) for e in errors), errors
            assert not store.has(KEY)
            assert not os.path.exists(
                os.path.join(str(store.root), "partial", KEY))
            # unpoisoned: the fault is spent, a fresh read lands clean
            got = ts.read(KEY, fetch=_http_fetch(chaos.url),
                          expected_digest=digest)
            assert got == body
            assert store.has(KEY)
        finally:
            ts.close()


# ------------------------------------------------------------ hot tier


def test_promotion_is_digest_verified(store):
    """Bytes that stop matching their content address are refused RAM:
    corrupting the on-disk object in place makes the next promotion fail
    (the mapped bytes hash to a digest the store never recorded)."""
    body = _blob(1)
    store.put(KEY, body, {"content-type": "application/octet-stream"})
    ts = tier.TieredStore(store, name="t-verify")
    try:
        assert ts.read(KEY) == body
        assert ts.hot.contains(KEY)
        ts.hot.invalidate(KEY)
        assert not ts.hot.contains(KEY)
        # flip one byte in place — same inode, wrong bytes
        path = os.path.join(str(store.root), "objects", KEY)
        fd = os.open(path, os.O_WRONLY)
        try:
            os.pwrite(fd, bytes([body[4096] ^ 0xFF]), 4096)
        finally:
            os.close(fd)
        assert ts.hot.promote(KEY) is False
        assert not ts.hot.contains(KEY)
    finally:
        ts.close()


def test_hot_tier_budget_bounded_lru(store):
    """The RAM tier never exceeds its byte budget: admitting past it
    demotes the least-recently-used mapping, and an object larger than
    the whole budget is refused outright."""
    one_mb = 1 << 20
    blob = _blob(1)[: 400 << 10]
    keys = ["lrukey000000000a", "lrukey000000000b", "lrukey000000000c"]
    for k in keys:
        store.put(k, blob, {"content-type": "application/octet-stream"})
    budget = tier.TierBudget("test-ram", one_mb)
    ts = tier.TieredStore(store, hot_budget=budget, name="t-lru")
    try:
        assert ts.hot.promote(keys[0])
        assert ts.hot.promote(keys[1])
        assert budget.over() == 0
        assert ts.hot.contains(keys[0]) and ts.hot.contains(keys[1])
        # a is older than b → admitting c demotes a
        assert ts.hot.promote(keys[2])
        assert budget.over() == 0
        assert not ts.hot.contains(keys[0])
        assert ts.hot.contains(keys[1]) and ts.hot.contains(keys[2])
        evicted = m.HUB.snapshot().get(
            m.labeled("store_tier_evicted_bytes_total", tier="ram"), 0)
        assert evicted >= len(blob)
        # an object bigger than the WHOLE budget never maps
        big = "lrukey000000000d"
        store.put(big, _blob(2)[: one_mb + 1],
                  {"content-type": "application/octet-stream"})
        assert ts.hot.promote(big) is False
    finally:
        ts.close()


def test_hot_reads_are_bytes_exact_copies(store):
    """A hot read returns a COPY of the mapped bytes — exact against the
    store, and still valid after the mapping is invalidated."""
    body = _blob(1)
    store.put(KEY, body, {"content-type": "application/octet-stream"})
    ts = tier.TieredStore(store, name="t-copy")
    try:
        first = ts.read(KEY)
        assert ts.hot.contains(KEY)
        second = ts.read(KEY)  # served from RAM
        ts.hot.invalidate(KEY)
        assert first == body and second == body
        snap = m.HUB.snapshot()
        assert snap.get(m.labeled("store_tier_hits_total", tier="ram"),
                        0) >= 1
    finally:
        ts.close()


# ---------------------------------------------------- generic collapse


def test_singleflight_do_collapses_and_hands_off(store):
    """``SingleFlight.do``: one leader runs the work; a failed leader
    hands the call to a waiter (the retry is ``fn`` again); waiters whose
    leader succeeded get None (they re-read the store)."""
    sf = tier.SingleFlight()
    calls = []
    gate = threading.Barrier(3)

    def work():
        calls.append(threading.get_ident())
        if len(calls) == 1:
            # hold the flight open until both waiters are queued, so the
            # failure provably hands off instead of finishing unobserved
            for _ in range(400):
                d = sf.describe()
                if d and d[0]["waiters"] >= 2:
                    break
                time.sleep(0.005)
            raise OSError("leader dies")
        return "landed"

    outcomes: list = [None] * 3

    def run(i):
        gate.wait(timeout=10)
        try:
            outcomes[i] = sf.do("dokey", work, timeout=20)
        except BaseException as e:  # noqa: BLE001 — asserted below
            outcomes[i] = e

    threads = [threading.Thread(target=run, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # exactly one retry after the handoff: the failed leader gets its
    # own error back, the taking-over waiter gets the result, the last
    # waiter gets None (re-read the store) — never the leader's error
    assert len(calls) == 2
    assert sum(1 for o in outcomes if isinstance(o, OSError)) == 1
    assert sum(1 for o in outcomes if o == "landed") == 1
    assert sum(1 for o in outcomes if o is None) == 1
    assert sf.in_flight() == 0


# ------------------------------------------- storage-fault plane edge

def test_tiny_budget_enospc_pull_avoids_degraded(store, monkeypatch):
    """A transient ENOSPC under a squeezed DEMODEL_CACHE_MAX_GB budget:
    the emergency enforce() eviction frees space, the single retry
    lands the chunk, and the node never enters degraded read-through —
    the tier sheds cached bytes, not the client's landing. (The
    persistent-ENOSPC shape, where the retry ALSO fails, lives in
    tests/test_disk_faults.py.)"""
    from .chaosdisk import DiskFaultPlan, DiskFaultSpec

    monkeypatch.setenv("DEMODEL_CACHE_MAX_GB", "1")
    store.put("fillerblob000001", _blob(1, seed=3), {})  # evictable
    body = _blob(2)
    calls = []

    def fetch(key, offset):
        calls.append((key, offset))
        for i in range(offset, len(body), 256 << 10):
            yield body[i:i + (256 << 10)]

    ts = tier.TieredStore(store, name="t-budget")
    try:
        with DiskFaultPlan(DiskFaultSpec("enospc", key=KEY,
                                         times=1)) as plan:
            assert ts.read(KEY, fetch=fetch) == body
            assert plan.fired("enospc") == 1
        assert calls == [(KEY, 0)]
        assert not ts.degraded()
        assert store.has(KEY)
        assert store.get(KEY) == body
        assert m.HUB.snapshot().get("store_degraded_entries_total") is None
    finally:
        ts.close()
