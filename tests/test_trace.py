"""Distributed-tracing subsystem (PR 5 tentpole): span nesting across
threads and asyncio, W3C traceparent round-trips through a real dep-light
peer fetch, buffer bounds, the disabled-tracing overhead guard, Chrome
export validity, and the acceptance path — a chaos pull with
``DEMODEL_TRACE`` set produces a JSONL trace showing window-read /
budget-wait / retry / failover stitched across client and peer, which
``tools/trace_report.py`` turns into a critical-path report.

Dep-light like the chaos matrix: warm peers are no-MITM ``ProxyServer``
nodes over directly-seeded stores (no ``cryptography``).
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import subprocess
import sys
import time
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path

import numpy as np
import pytest

from demodel_tpu.utils import metrics as m
from demodel_tpu.utils import trace
from demodel_tpu.utils.faults import PeerHealth

from .chaoshttp import ChaosPeer, FaultPlan, FaultSpec
from .test_fault_injection import MODEL, _assert_exact, _seed_store

REPO = Path(__file__).resolve().parent.parent

#: disabled-tracing budget per span enter/exit. A no-op span is one
#: module-global check + a shared context manager (~0.5 µs even on a
#: loaded 1-CPU CI container); 5 µs holds a 10× margin while still
#: catching an accidental allocation/clock-read on the fast path.
NOOP_BUDGET_SECS = 5e-6


@pytest.fixture(autouse=True)
def _fresh_trace_state(monkeypatch, tmp_path):
    monkeypatch.delenv("DEMODEL_TRACE", raising=False)
    monkeypatch.delenv("DEMODEL_TRACE_BUFFER", raising=False)
    monkeypatch.delenv("DEMODEL_TRACE_SAMPLE", raising=False)
    monkeypatch.delenv("DEMODEL_OBS", raising=False)
    # error-status roots in these tests must not litter the real tempdir
    # with autodump files (the recorder is ALWAYS on by design)
    monkeypatch.setenv("DEMODEL_RECORDER_DIR", str(tmp_path))
    trace.reset()
    m.HUB.reset()
    PeerHealth.reset_shared()
    yield
    trace.reset()
    PeerHealth.reset_shared()


def _records():
    return trace.buffer().snapshot()


def _by_name(name):
    return [r for r in _records() if r["name"] == name]


# ------------------------------------------------------------ fundamentals


def test_disabled_span_is_noop_and_cheap(monkeypatch):
    """The overhead guard: with observability fully OFF (DEMODEL_OBS=0 —
    the kill switch below the default observe tier), span() must return
    the shared no-op after one global check — no allocation, no clock."""
    monkeypatch.setenv("DEMODEL_OBS", "0")
    trace.reset()
    assert not trace.enabled()
    assert not trace.active()
    s = trace.span("anything", key="value")
    assert s is trace.NOOP
    assert trace.current() is None
    assert trace.traceparent() is None
    n = 100_000
    t0 = time.perf_counter()
    for _ in range(n):
        with trace.span("hot-path"):
            pass
    per_op = (time.perf_counter() - t0) / n
    assert per_op < NOOP_BUDGET_SECS, (
        f"disabled span enter/exit costs {per_op * 1e6:.2f}µs "
        f"(budget {NOOP_BUDGET_SECS * 1e6:.0f}µs)")


def test_wrap_is_identity_when_disabled():
    fn = lambda: 1  # noqa: E731
    assert trace.wrap(fn) is fn


def test_parent_child_nesting_same_thread():
    trace.enable()
    with trace.span("parent") as p:
        assert trace.current() is p
        with trace.span("child") as c:
            assert c.trace_id == p.trace_id
            assert c.parent_id == p.span_id
        assert trace.current() is p
    assert trace.current() is None
    recs = _records()
    assert [r["name"] for r in recs] == ["child", "parent"]  # finish order
    assert recs[0]["parent"] == recs[1]["span"]


def test_error_status_recorded():
    trace.enable()
    with pytest.raises(ValueError):
        with trace.span("doomed"):
            raise ValueError("boom")
    (rec,) = _by_name("doomed")
    assert rec["status"] == "error"
    assert "ValueError: boom" in rec["error"]


def test_span_events_carry_offsets():
    trace.enable()
    with trace.span("op") as sp:
        sp.event("retry", attempt=1)
        trace.event("ambient", via="module-helper")
    (rec,) = _by_name("op")
    names = [e["name"] for e in rec["events"]]
    assert names == ["retry", "ambient"]
    assert all(e["t"] >= 0 for e in rec["events"])


def test_thread_propagation_needs_wrap():
    """contextvars do NOT cross threading; trace.wrap captures the
    ambient span at the submit site."""
    trace.enable()

    def child_op():
        with trace.span("t-child"):
            pass

    with ThreadPoolExecutor(max_workers=1) as ex:
        with trace.span("t-root") as root:
            ex.submit(trace.wrap(child_op)).result()   # wrapped: parented
            ex.submit(child_op).result()               # bare: orphaned
    wrapped, orphan = _by_name("t-child")
    assert wrapped["parent"] == root.span_id
    assert wrapped["trace"] == root.trace_id
    assert orphan["parent"] is None
    assert orphan["trace"] != root.trace_id


def test_wrap_per_submit_survives_concurrent_workers():
    """A contextvars.Context is single-entrant: one shared wrapped fn
    across a pool raised 'cannot enter context' on the first concurrent
    pair (review finding). Wrapping PER SUBMIT gives each worker its own
    Context copy — N simultaneous children must all run and parent."""
    import threading as _threading

    trace.enable()
    gate = _threading.Barrier(4)

    def child_op(i):
        gate.wait(timeout=30)  # force 4 wrapped contexts entered at once
        with trace.span("c-child", i=i):
            pass
        return i

    with ThreadPoolExecutor(max_workers=4) as ex:
        with trace.span("c-root") as root:
            futs = [ex.submit(trace.wrap(child_op), i) for i in range(4)]
            assert sorted(f.result() for f in futs) == [0, 1, 2, 3]
    children = _by_name("c-child")
    assert len(children) == 4
    assert all(c["parent"] == root.span_id for c in children)


def test_asyncio_propagation_is_automatic():
    trace.enable()

    async def main():
        with trace.span("a-root") as root:
            async def sub(i):
                with trace.span("a-child", i=i):
                    await asyncio.sleep(0)

            await asyncio.gather(asyncio.create_task(sub(0)),
                                 asyncio.create_task(sub(1)))
            return root

    root = asyncio.run(main())
    children = _by_name("a-child")
    assert len(children) == 2
    assert all(c["parent"] == root.span_id for c in children)
    assert all(c["trace"] == root.trace_id for c in children)


def test_traceparent_roundtrip_and_malformed_headers():
    trace.enable()
    with trace.span("origin") as sp:
        tp = trace.traceparent()
        assert tp == f"00-{sp.trace_id}-{sp.span_id}-01"
        assert trace.parse_traceparent(tp) == (sp.trace_id, sp.span_id)
        hdrs = trace.inject_headers({"Range": "bytes=0-1"})
        assert hdrs["traceparent"] == tp
        assert hdrs["Range"] == "bytes=0-1"
    # peer input never raises
    for bad in ("", "junk", "00-short-ffff-01", "xx-" + "0" * 32 + "-" +
                "0" * 16 + "-01", "00-" + "g" * 32 + "-" + "1" * 16 + "-01"):
        assert trace.parse_traceparent(bad) is None
    # remote parenting: a child of a wire-carried context
    with trace.span("server-side", remote_parent=tp) as child:
        assert child.trace_id == sp.trace_id
        assert child.parent_id == sp.span_id


def test_buffer_is_bounded(monkeypatch):
    monkeypatch.setenv("DEMODEL_TRACE_BUFFER", "16")
    trace.reset()
    trace.enable()
    for i in range(100):
        with trace.span("filler", i=i):
            pass
    buf = trace.buffer()
    assert len(buf) == 16
    assert buf.dropped == 84
    # newest survive
    assert buf.snapshot()[-1]["attrs"]["i"] == 99


def test_metrics_summaries_on_exposition():
    trace.enable()
    with trace.span("window-read"):
        pass
    with trace.span("window-read"):
        pass
    label = 'trace_spans_total{span="window-read"}'
    assert m.HUB.get(label) == 2
    secs = m.HUB.get('trace_span_seconds_total{span="window-read"}')
    assert secs >= 0
    text = m.render()
    assert "# TYPE demodel_trace_spans_total counter" in text
    assert 'demodel_trace_spans_total{span="window-read"} 2' in text


def test_chrome_export_shape(tmp_path):
    trace.enable()
    with trace.span("outer", model="gpt2") as sp:
        sp.event("fault", kind="reset-at-byte")
        with trace.span("inner"):
            pass
    out = tmp_path / "chrome.json"
    n = trace.dump_chrome(str(out))
    doc = json.loads(out.read_text())
    events = doc["traceEvents"]
    assert len(events) == n == 3  # two X spans + one instant
    for ev in events:
        assert ev["ph"] in ("X", "i")
        for k in ("name", "ts", "pid", "tid", "cat"):
            assert k in ev
        if ev["ph"] == "X":
            assert ev["dur"] >= 0
    assert any(ev["name"] == "outer:fault" for ev in events)


def test_jsonl_sink_writes_parseable_lines(tmp_path, monkeypatch):
    path = tmp_path / "sink.jsonl"
    monkeypatch.setenv("DEMODEL_TRACE", str(path))
    trace.reset()
    assert trace.enabled()
    with trace.span("a"):
        with trace.span("b"):
            pass
    lines = path.read_text().strip().splitlines()
    recs = [json.loads(ln) for ln in lines]
    assert [r["name"] for r in recs] == ["b", "a"]
    assert recs[0]["trace"] == recs[1]["trace"]


# ------------------------------------------ head sampling (serve traffic)


def test_sample_zero_drops_whole_traces(monkeypatch):
    """DEMODEL_TRACE_SAMPLE=0: a new root drops from the EXPORT and its
    descendants drop WITH it — never re-rolled into orphan fragments.
    The spans still RUN: sampling is an export-volume knob, so the
    always-on surfaces (flight recorder, stage histograms) stay whole."""
    monkeypatch.setenv("DEMODEL_TRACE_SAMPLE", "0")
    trace.enable()
    with trace.span("root") as root:
        assert isinstance(root, trace.Span)
        with trace.span("child") as child:
            assert isinstance(child, trace.Span)
            assert child.trace_id == root.trace_id
    assert _records() == []  # nothing exported
    assert {r["name"] for r in trace.recorder().snapshot()} == {
        "root", "child"}  # recorder unaffected by the export knob
    assert m.HUB.get_histogram(
        m.labeled("stage_duration_seconds", span="root")) is not None


def test_sample_one_records_everything(monkeypatch):
    monkeypatch.setenv("DEMODEL_TRACE_SAMPLE", "1.0")
    trace.enable()
    with trace.span("root"):
        with trace.span("child"):
            pass
    assert {r["name"] for r in _records()} == {"root", "child"}


def test_sample_decision_is_per_root(monkeypatch):
    """The dice roll happens once per ROOT span; children inherit the
    keep/drop decision from the ambient context."""
    monkeypatch.setenv("DEMODEL_TRACE_SAMPLE", "0.5")
    trace.enable()
    rolls = iter([0.2, 0.9, 0.2])  # keep, drop, keep (rate 0.5)
    monkeypatch.setattr(trace.random, "random", lambda: next(rolls))
    with trace.span("kept"):
        pass
    with trace.span("dropped"):
        with trace.span("dropped-child"):
            pass
    with trace.span("kept2"):
        pass
    assert [r["name"] for r in _records()] == ["kept", "kept2"]


def test_remote_parented_span_bypasses_sampling(monkeypatch):
    """A traceparent from the wire means the CALLING host already made the
    keep decision — the serving side must not drop its half of the trace."""
    monkeypatch.setenv("DEMODEL_TRACE_SAMPLE", "0")
    trace.enable()
    tp = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
    with trace.span("serve", remote_parent=tp):
        pass
    (rec,) = _by_name("serve")
    assert rec["trace"] == "ab" * 16


def test_unsampled_root_crosses_wrap(monkeypatch):
    """A dropped trace's thread fan-out must not re-roll per task: wrap()
    carries the unsampled mark across the executor boundary, so the
    task's span runs but drops from the export with its root."""
    monkeypatch.setenv("DEMODEL_TRACE_SAMPLE", "0")
    trace.enable()
    out = []
    with trace.span("root"):
        fn = trace.wrap(lambda: out.append(trace.span("task")))
    with ThreadPoolExecutor(max_workers=1) as ex:
        ex.submit(fn).result()
    (task,) = out
    assert isinstance(task, trace.Span)
    task.finish()
    assert _records() == []


def test_malformed_sample_rate_records_everything(monkeypatch):
    monkeypatch.setenv("DEMODEL_TRACE_SAMPLE", "lots")
    trace.enable()
    with trace.span("root"):
        pass
    assert _by_name("root")


# ----------------------------------------------------- streaming-sink spans


def _sink_with_fake_delivery(monkeypatch, delivered):
    from demodel_tpu.sink import streaming as st_mod
    from demodel_tpu.sink.hbm import Placement

    def fake_deliver(store, name, key, mesh, plan, cast_to, buffer=None,
                     ici_complete=None):
        delivered.append(name)
        return Placement(mesh_desc="fake")

    monkeypatch.setattr(st_mod, "deliver_file", fake_deliver)
    return st_mod.StreamingSink(store=None, overlap=True)


def test_streaming_sink_deliver_span_stitches_to_submitter(monkeypatch):
    """sink-deliver runs on the sink's worker thread; the submit site's
    ambient span must reach it as its trace parent (carried across the
    queue as a traceparent), so pull traces show where HBM time went."""
    trace.enable()
    delivered: list[str] = []
    sink = _sink_with_fake_delivery(monkeypatch, delivered)

    class Art:
        name = "model-00001-of-00002.safetensors"
        key = "k" * 16
        media_type = ""

    with trace.span("pull-root") as root:
        sink.submit(Art())
        root_trace = root.trace_id
    sink.finish(block=False)
    assert delivered == [Art.name]
    (rec,) = _by_name("sink-deliver")
    assert rec["trace"] == root_trace
    assert rec["attrs"]["file"] == Art.name
    assert rec["attrs"]["tensors"] == 0  # fake placement carries none


def test_streaming_sink_respects_head_sampling(monkeypatch):
    """A sampled-OUT pull must not leak orphan sink-deliver roots from the
    worker side of the queue: the suppression verdict crosses with the
    item (contextvars cannot follow it there)."""
    monkeypatch.setenv("DEMODEL_TRACE_SAMPLE", "0")
    trace.enable()
    delivered: list[str] = []
    sink = _sink_with_fake_delivery(monkeypatch, delivered)

    class Art:
        name = "model.safetensors"
        key = "k" * 16
        media_type = ""

    with trace.span("pull-root"):  # unsampled root (rate 0)
        sink.submit(Art())
    sink.finish(block=False)
    assert delivered == [Art.name]  # delivery itself still happened
    assert _records() == []


def test_streaming_sink_budget_wait_span(monkeypatch):
    """A standalone producer charging the byte budget at submit() gets a
    sink-budget-wait span — the stall the budget can introduce is visible
    in the trace, not silent."""
    import numpy as np_mod

    trace.enable()
    delivered: list[str] = []
    sink = _sink_with_fake_delivery(monkeypatch, delivered)

    class Art:
        name = "model.safetensors"
        key = "k" * 16
        media_type = ""
        buffer = np_mod.zeros(64, dtype=np_mod.uint8)

    sink.submit(Art())
    sink.finish(block=False)
    (rec,) = _by_name("sink-budget-wait")
    assert rec["attrs"] == {"file": Art.name, "bytes": 64}


# --------------------------------------------- wire round-trip (dep-light)


@contextlib.contextmanager
def _warm_nodes(tmp_path, count=1, n_shards=3):
    """``count`` live no-MITM peers all seeded with the SAME model bytes
    (same tag/seed → same store keys and digests), so window failover has
    a real alternative source."""
    from demodel_tpu.config import ProxyConfig
    from demodel_tpu.proxy import ProxyServer
    from demodel_tpu.store import Store

    nodes, seeded = [], None
    try:
        for i in range(count):
            cfg = ProxyConfig(
                host="127.0.0.1", port=0, mitm_hosts=[], no_mitm=True,
                cache_dir=tmp_path / f"peer{i}-cache",
                data_dir=tmp_path / f"peer{i}-data")
            store = Store(cfg.cache_dir / "proxy")
            try:
                seeded = _seed_store(store, "tracetag", n_shards, seed=7)
            finally:
                store.close()
            node = ProxyServer(cfg, verbose=False)
            node.start()
            nodes.append(node)
        yield nodes, seeded
    finally:
        for node in nodes:
            node.stop()


@pytest.fixture()
def _fast_wire(monkeypatch):
    monkeypatch.setenv("DEMODEL_RETRY_BASE_MS", "20")
    monkeypatch.setenv("DEMODEL_RETRY_DEADLINE", "60")
    monkeypatch.setenv("DEMODEL_BREAKER_COOLDOWN", "1")
    monkeypatch.setenv("DEMODEL_PROXY_IDLE_TIMEOUT", "1")


def test_traceparent_roundtrip_through_real_peer_fetch(tmp_path, _fast_wire):
    """A client window read against a REAL dep-light peer (through the
    Python shim that extracts traceparent) stitches: the server-side span
    carries the client span's trace id and parents on it."""
    from demodel_tpu.sink.remote import PeerBlobReader

    trace.enable()
    with _warm_nodes(tmp_path) as (nodes, (tensors, files, _)):
        plan = FaultPlan()  # no faults: pure propagation
        with ChaosPeer(nodes[0].url, plan) as shim:
            f = files[0]
            reader = PeerBlobReader(shim.url, f["key"], f["size"])
            out = np.empty(f["size"], dtype=np.uint8)
            assert reader.pread_into(f["key"], out, 0) == f["size"]

    (client,) = _by_name("window-read")
    serves = _by_name("serve.peer")
    assert serves, "peer shim emitted no server-side spans"
    stitched = [s for s in serves if s["trace"] == client["trace"]]
    assert stitched, (serves, client)
    assert any(s["parent"] == client["span"] for s in stitched)


# ------------------------------------------------- acceptance: chaos pull


def test_traced_chaos_pull_end_to_end(tmp_path, _fast_wire, monkeypatch):
    """The ISSUE acceptance path: a chaos pull (mid-window RST, failover
    to a second warm peer) with ``DEMODEL_TRACE`` set produces a JSONL
    trace that (a) parses, (b) shows window-read / budget-wait /
    retry / failover stitched across client and peer via traceparent,
    (c) converts to valid Chrome trace-event JSON, and (d) yields a
    critical-path report from ``tools/trace_report.py``."""
    jsonl = tmp_path / "pull.jsonl"
    monkeypatch.setenv("DEMODEL_TRACE", str(jsonl))
    # this test pins the TRACE SHAPE of a faulted pull; the adaptive
    # tuner (its own root span, sub-window splitting, a tick thread
    # competing for this 1-CPU box) is pinned off — its in-pull
    # integration is covered by test_tuner.py
    monkeypatch.setenv("DEMODEL_TUNER", "0")
    trace.reset()

    from demodel_tpu.sink.remote import pull_manifest_to_hbm

    with _warm_nodes(tmp_path, count=2) as (nodes, (tensors, files, _)):
        plan = FaultPlan(
            FaultSpec(kind="reset-at-byte", path="/peer/object",
                      times=1, at_byte=1 << 20, min_body=1 << 21),
        )
        with ChaosPeer(nodes[0].url, plan) as shim0, \
                ChaosPeer(nodes[1].url, FaultPlan()) as shim1:
            report, placed = pull_manifest_to_hbm(
                MODEL, [shim0.url, shim1.url])
    _assert_exact(placed, tensors)
    assert plan.fired("reset-at-byte") == 1

    # (a) the JSONL parses, line by line
    recs = [json.loads(ln) for ln in
            jsonl.read_text().strip().splitlines()]
    names = {r["name"] for r in recs}
    assert {"pull", "manifest-discovery", "window-read", "budget-wait",
            "place", "http.request", "serve.peer"} <= names, names

    # (b) one trace end-to-end: everything hangs off the pull root,
    # including the peer-side serve spans (traceparent stitch), and the
    # faulted window carries retry + failover events
    (root,) = [r for r in recs if r["name"] == "pull"]
    assert root["parent"] is None
    in_trace = [r for r in recs if r["trace"] == root["trace"]]
    assert {"window-read", "budget-wait", "serve.peer"} <= {
        r["name"] for r in in_trace}
    events = [(e["name"], e.get("attrs", {}))
              for r in in_trace for e in r.get("events", ())]
    assert any(n == "retry" for n, _ in events), events
    assert any(n == "failover" for n, _ in events), events
    assert any(n == "fault" and a.get("kind") == "reset-at-byte"
               for n, a in events), events
    # the faulted window failed over to the OTHER peer, resuming at the
    # received offset. The linger-0 RST discards whatever the client had
    # not yet drained from the kernel buffer, so a slow-scheduled reader
    # legitimately resumes at 0 — exact positive-offset resume is pinned
    # by the Range-log tests in test_fault_injection; here the contract
    # is the trace shape, and the retry event must agree with the
    # failover on where the resume happened
    failovers = [a for n, a in events if n == "failover"]
    assert failovers, events
    assert all(a["from_peer"] != a["to_peer"] for a in failovers)
    retry_offsets = {a["resume_at"] for n, a in events if n == "retry"}
    assert any(a["resume_at"] in retry_offsets for a in failovers), events

    # (c+d) the report tool: one JSON line + a Perfetto-loadable file
    chrome = tmp_path / "pull.json"
    proc = subprocess.run(
        [sys.executable, "tools/trace_report.py", str(jsonl),
         "--chrome", str(chrome)],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["metric"] == "trace_report"
    assert out["spans"] == len(recs)
    assert out["critical_path"], out
    assert out["critical_path"][0]["name"] == "pull"
    assert "window-read" in out["stages"]
    assert out["stages"]["window-read"]["count"] >= 3
    assert abs(out["wall_secs"] - root["dur"]) < 1e-6

    doc = json.loads(chrome.read_text())
    events = doc["traceEvents"]
    assert events and out["chrome_events"] == len(events)
    for ev in events:
        assert ev["ph"] in ("X", "i")
        assert isinstance(ev["ts"], float) and ev["ts"] > 0
    assert any(ev["name"] == "pull" for ev in events)


def test_trace_report_critical_path_synthetic(tmp_path):
    """The critical-path walk on a hand-built trace: root(10) covers
    fetch(7, ends at 9) which covers wait(6, ends at 8.5) — the chain and
    self-times must come out exactly."""
    rows = [
        {"trace": "t1", "span": "r", "parent": None, "name": "root",
         "ts": 100.0, "dur": 10.0, "pid": 1, "tid": 1, "status": "ok"},
        {"trace": "t1", "span": "f", "parent": "r", "name": "fetch",
         "ts": 102.0, "dur": 7.0, "pid": 1, "tid": 1, "status": "ok"},
        {"trace": "t1", "span": "w", "parent": "f", "name": "wait",
         "ts": 102.5, "dur": 6.0, "pid": 1, "tid": 1, "status": "ok"},
        # an early, short sibling that must NOT appear on the path
        {"trace": "t1", "span": "s", "parent": "r", "name": "setup",
         "ts": 100.1, "dur": 0.5, "pid": 1, "tid": 1, "status": "ok"},
    ]
    p = tmp_path / "synth.jsonl"
    p.write_text("\n".join(json.dumps(r) for r in rows))
    proc = subprocess.run(
        [sys.executable, "tools/trace_report.py", str(p)],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    out = json.loads(proc.stdout)
    chain = [(e["name"], e["secs"]) for e in out["critical_path"]]
    assert chain[:3] == [("root", 10.0), ("fetch", 7.0), ("wait", 6.0)]
    # root's critical cover: fetch(7) then setup(0.5) fits before it
    assert out["critical_path"][0]["self_secs"] == pytest.approx(2.5)
    assert out["critical_path"][1]["self_secs"] == pytest.approx(1.0)
    assert out["wall_secs"] == 10.0
    assert out["stages"]["root"]["count"] == 1


def test_trace_report_terminates_on_zero_duration_spans(tmp_path):
    """Regression (review finding): a zero-duration span ending exactly
    at its parent's end used to be re-selected forever by the gating-
    child walk — the reporter must terminate and still report."""
    rows = [
        {"trace": "t", "span": "r", "parent": None, "name": "root",
         "ts": 0.0, "dur": 10.0, "pid": 1, "tid": 1, "status": "ok"},
        {"trace": "t", "span": "z", "parent": "r", "name": "zero",
         "ts": 10.0, "dur": 0.0, "pid": 1, "tid": 1, "status": "ok"},
        {"trace": "t", "span": "w", "parent": "r", "name": "work",
         "ts": 1.0, "dur": 8.0, "pid": 1, "tid": 1, "status": "ok"},
    ]
    p = tmp_path / "zero.jsonl"
    p.write_text("\n".join(json.dumps(r) for r in rows))
    proc = subprocess.run(
        [sys.executable, "tools/trace_report.py", str(p)],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    out = json.loads(proc.stdout)
    assert out["wall_secs"] == 10.0
    names = [e["name"] for e in out["critical_path"]]
    assert names[0] == "root" and "zero" in names
