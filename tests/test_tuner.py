"""The adaptive pull tuner: AIMD transitions, knob bounds, the
DEMODEL_TUNER=0 kill switch, and the tuned fetch loop over a real
dep-light peer.

The controller is driven with FORCED signals (tick's keyword seams) so
every transition is deterministic: probe upward on a stable delivery
rate, revert a probe that cost throughput, multiplicative back-off on a
retry storm / open breaker, prefetch decrease under budget pressure —
each decision visible as a span event and ``tuner_*`` gauges.
"""

from __future__ import annotations

import hashlib
import threading
import time

import numpy as np
import pytest

from demodel_tpu.sink.tuner import (
    PullTuner,
    current,
    fetch_windows,
    tuner_enabled,
)
from demodel_tpu.utils import metrics as m
from demodel_tpu.utils import trace
from demodel_tpu.utils.faults import PeerHealth


@pytest.fixture(autouse=True)
def _fresh_state():
    trace.reset()
    m.HUB.reset()
    PeerHealth.reset_shared()
    yield
    trace.reset()
    m.HUB.reset()
    PeerHealth.reset_shared()


def _tuner(**kw):
    kw.setdefault("prefetch_depth", 2)
    kw.setdefault("tick_s", 0.01)
    kw.setdefault("window_s", 5)
    return PullTuner(**kw)


def test_enabled_switch(monkeypatch):
    monkeypatch.delenv("DEMODEL_TUNER", raising=False)
    assert tuner_enabled() is True
    monkeypatch.setenv("DEMODEL_TUNER", "0")
    assert tuner_enabled() is False
    monkeypatch.setenv("DEMODEL_TUNER", "off")
    assert tuner_enabled() is False


def test_additive_increase_probes_one_knob_at_a_time():
    t = _tuner()
    start = t.snapshot()
    t.tick(thr=100.0, retry_rate=0.0, breaker_open=False,
           budget_wait_share=0.0)
    after = t.snapshot()
    changed = [k for k in ("streams", "window_bytes", "prefetch_depth")
               if after[k] != start[k]]
    assert len(changed) == 1, "a probe raises exactly one knob"
    assert t.decisions == 1


def test_probe_reverts_when_throughput_drops():
    t = _tuner()
    knobs = ("streams", "window_bytes", "prefetch_depth")
    start = {k: t.snapshot()[k] for k in knobs}
    t.tick(thr=1000.0, retry_rate=0.0, breaker_open=False,
           budget_wait_share=0.0)
    assert {k: t.snapshot()[k] for k in knobs} != start
    # the probe cost 40% throughput: next tick reverts it and holds
    t.tick(thr=600.0, retry_rate=0.0, breaker_open=False,
           budget_wait_share=0.0)
    assert {k: t.snapshot()[k] for k in knobs} == start
    t.tick(thr=600.0, retry_rate=0.0, breaker_open=False,
           budget_wait_share=0.0)
    assert {k: t.snapshot()[k] for k in knobs} == start, \
        "the post-revert hold blocks re-probing"


def test_multiplicative_backoff_on_retry_storm_and_breaker():
    t = _tuner()
    for _ in range(6):  # drive knobs up first
        t.tick(thr=100.0 + t.decisions, retry_rate=0.0,
               breaker_open=False, budget_wait_share=0.0)
    up = t.snapshot()
    assert up["streams"] > 1 or up["window_bytes"] > 32 << 20
    t.tick(thr=500.0, retry_rate=2.0, breaker_open=False,
           budget_wait_share=0.0)
    down = t.snapshot()
    assert down["streams"] <= max(1, up["streams"] // 2)
    assert down["window_bytes"] <= up["window_bytes"] // 2
    # breaker-open triggers the same path (after the hold expires)
    t2 = _tuner(clock=lambda: time.monotonic() + 3600)
    t2.streams = 4
    t2.tick(thr=0.0, retry_rate=0.0, breaker_open=True,
            budget_wait_share=0.0)
    assert t2.streams == 2


def test_knob_bounds_are_respected():
    t = _tuner()
    # a non-power-of-two start would overshoot the ceiling if the
    # doubling probe didn't clamp (48 → 96 → 192 → 384 > 256 MB)
    t.window_bytes = 48 << 20
    for _ in range(200):
        t.tick(thr=1e9, retry_rate=0.0, breaker_open=False,
               budget_wait_share=0.0)
    assert t.streams <= t.max_streams
    assert t.window_bytes <= t.max_window
    assert t.prefetch_depth <= t.max_prefetch
    # storm it down repeatedly: floors hold
    clock = {"t": 0.0}
    t2 = _tuner(clock=lambda: clock["t"])
    for i in range(50):
        clock["t"] = float(i * 100)
        t2.tick(thr=0.0, retry_rate=9.0, breaker_open=False,
                budget_wait_share=0.0)
    assert t2.streams == t2.min_streams == 1
    assert t2.window_bytes == t2.min_window
    assert t2.prefetch_depth == 1


def test_prefetch_zero_stays_zero():
    # a pull resolved to prefetch 0 (single-core CPU backend) must not
    # have prefetch forced on by the tuner — the contention is measured
    t = _tuner(prefetch_depth=0)
    for _ in range(20):
        t.tick(thr=100.0, retry_rate=0.0, breaker_open=False,
               budget_wait_share=0.0)
    assert t.prefetch_depth == 0


def test_live_probe_settles_then_judges_post_raise_window():
    """The LIVE path (no forced seams): a probe must not be judged one
    tick later against the long moving average — it settles for
    ``judge_s`` and is then judged over ONLY the post-raise interval, so
    a raise that collapses delivery really does revert."""
    feed = {"counters": {"pull_bytes_total": 0.0}, "gauges": {},
            "hists": {}}
    clock = {"t": 0.0}
    tel = m.Telemetry(
        lambda: {"counters": dict(feed["counters"]), "gauges": {},
                 "hists": {}},
        cap=256, min_gap_s=0.0, clock=lambda: clock["t"])
    t = PullTuner(prefetch_depth=2, tick_s=0.5, window_s=30.0,
                  telemetry=tel, clock=lambda: clock["t"])

    def advance(rate_bps, ticks):
        for _ in range(ticks):
            clock["t"] += t.tick_s
            feed["counters"]["pull_bytes_total"] += rate_bps * t.tick_s
            t.tick()

    # drive at a healthy 100 B/s until a probe with a MEASURED positive
    # baseline is pending (the very first probe sees an empty ring and a
    # zero base, which the revert guard deliberately ignores)
    for _ in range(100):
        if t._probe is not None and t._probe_base > 0:
            break
        advance(100.0, 1)
    else:
        pytest.fail("no measured-baseline probe ever fired")
    probed_knob, old_val = t._probe
    assert getattr(t, probed_knob) != old_val
    # the raise HURTS: delivery collapses to 10 B/s. Strictly inside
    # judge_s the probe must stay pending (settling); once the settle
    # window has passed, the post-raise-window rate triggers the revert.
    pending_since = t._probe_t
    while clock["t"] + t.tick_s < pending_since + t.judge_s:
        advance(10.0, 1)
        assert t._probe is not None, "judged before the raise settled"
    advance(10.0, 2)
    assert t._probe is None
    assert getattr(t, probed_knob) == old_val, \
        "a probe that collapsed delivery must revert"
    h = m.HUB.snapshot()
    assert h.get('tuner_decisions_total{action="revert"}', 0) >= 1


def test_budget_pressure_decreases_prefetch():
    class Budget:
        max_bytes = 1 << 30
        in_use = 0

    t = _tuner(prefetch_depth=4, budget=Budget())
    t.tick(thr=100.0, retry_rate=0.0, breaker_open=False,
           budget_wait_share=0.9)
    assert t.prefetch_depth == 3
    h = m.HUB.snapshot()
    assert h['tuner_decisions_total{action="decrease"}'] == 1


def test_budget_headroom_gates_prefetch_raise():
    class Full:
        max_bytes = 1 << 20
        in_use = 1 << 20  # zero headroom

    t = _tuner(prefetch_depth=2, budget=Full())
    # exhaust the other knobs so only prefetch would remain
    t.streams = t.max_streams
    t.window_bytes = t.max_window
    for _ in range(10):
        # hbm_pressure forced quiet: this test isolates the RAISE gate
        # (the full budget would otherwise trip the device-shed path,
        # covered by test_hbm_pressure_sheds_prefetch_and_gates_probe)
        t.tick(thr=100.0, retry_rate=0.0, breaker_open=False,
               budget_wait_share=0.0, hbm_pressure=0.0)
    assert t.prefetch_depth == 2, \
        "no budget headroom → no prefetch probe"


def test_place_latency_pressure_sheds_prefetch():
    """The device-fed loop: a slow place/sink-deliver p99 (forced via the
    tick seam) sheds prefetch depth BEFORE the admission-wait signal is
    even consulted — depth is what converts place latency into pinned
    host RAM."""
    t = _tuner(prefetch_depth=4)
    t.tick(thr=100.0, retry_rate=0.0, breaker_open=False,
           budget_wait_share=0.0, place_p99=5.0)
    assert t.prefetch_depth == 3
    h = m.HUB.snapshot()
    assert h['tuner_decisions_total{action="decrease"}'] == 1
    assert m.HUB.gauges()["tuner_place_p99"] == pytest.approx(5.0)


def test_hbm_pressure_sheds_prefetch_and_gates_probe():
    class Budget:
        max_bytes = 1 << 30
        in_use = 0

    t = _tuner(prefetch_depth=3, budget=Budget())
    t.tick(thr=100.0, retry_rate=0.0, breaker_open=False,
           budget_wait_share=0.0, hbm_pressure=0.95)
    assert t.prefetch_depth == 2
    assert m.HUB.gauges()["tuner_hbm_pressure"] == pytest.approx(0.95)
    # at the floor, sustained pressure must also gate the upward probe:
    # prefetch never rises while the device plane is the bottleneck
    t2 = _tuner(prefetch_depth=1)
    t2.streams = t2.max_streams
    t2.window_bytes = t2.max_window
    for _ in range(10):
        t2.tick(thr=100.0, retry_rate=0.0, breaker_open=False,
                budget_wait_share=0.0, hbm_pressure=0.95)
    assert t2.prefetch_depth == 1


def test_device_shed_is_a_span_event():
    """A LIVE tick thread reading a charged budget sheds prefetch and the
    decision lands on the tuner span with the device reason — the
    acceptance shape: signal → shed → span event + decision counter."""
    class Charged:
        max_bytes = 1 << 20
        in_use = 1 << 20  # fully charged: hbm_pressure 1.0

    t = _tuner(prefetch_depth=3, budget=Charged())
    t.start()
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and t.prefetch_depth > 1:
            time.sleep(0.02)
    finally:
        t.stop()
    assert t.prefetch_depth == 1  # shed to the floor, never below
    h = m.HUB.snapshot()
    assert h.get('tuner_decisions_total{action="decrease"}', 0) >= 2
    recs = [r for r in trace.recorder().snapshot() if r["name"] == "tuner"]
    reasons = [e["attrs"]["reason"] for r in recs
               for e in r.get("events", ()) if e["name"] == "tune"]
    assert any("hbm-pressure" in r for r in reasons), reasons


def test_device_signals_default_from_telemetry_and_budget():
    """Unforced ticks read the live planes: the place-stage histogram
    feeds place_p99 and the ByteBudget's charge feeds hbm_pressure."""
    class Charged:
        max_bytes = 1 << 20
        in_use = (1 << 20) - 1024

    t = _tuner(prefetch_depth=2, budget=Charged())
    tel = t._tel()
    tel.sample()
    m.HUB.observe(m.labeled("stage_duration_seconds", span="place"), 2.0)
    time.sleep(0.01)
    tel.sample()
    t.tick(retry_rate=0.0, breaker_open=False, budget_wait_share=0.0)
    g = m.HUB.gauges()
    assert g["tuner_place_p99"] > 1.0
    assert g["tuner_hbm_pressure"] == pytest.approx(1023 / 1024, rel=1e-3)
    # and the derived pressure drove the same shed path
    assert t.prefetch_depth == 1


def test_decisions_are_span_events_and_gauges():
    t = _tuner()
    t.start()
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and t.decisions == 0:
            time.sleep(0.02)
        assert current() is t
    finally:
        t.stop()
    assert current() is None
    g = m.HUB.gauges()
    assert "tuner_streams" in g and "tuner_window_bytes" in g
    assert "tuner_prefetch_depth" in g and "tuner_throughput_bps" in g
    # the tuner span landed in the flight recorder with tune events
    recs = [r for r in trace.recorder().snapshot() if r["name"] == "tuner"]
    assert recs, "tuner root span must finish into the recorder"
    events = [e for r in recs for e in r.get("events", ())
              if e["name"] == "tune"]
    assert events and {"action", "knob", "frm", "to", "reason"} <= \
        set(events[0]["attrs"])


def test_fetch_windows_splits_by_live_knob_and_sets_streams():
    class Reader:
        def __init__(self):
            self.calls = []
            self.streams = 99

        def pread_into(self, key, view, offset):
            self.calls.append((offset, view.nbytes))
            view[:] = b"\x07" * view.nbytes
            return view.nbytes

    t = _tuner()
    t.window_bytes = 4096
    t.streams = 3
    r = Reader()
    buf = bytearray(10000)
    assert fetch_windows(r, "k", buf, 100, t) == 10000
    assert r.calls == [(100, 4096), (4196, 4096), (8292, 1808)]
    assert r.streams == 3
    assert bytes(buf) == b"\x07" * 10000
    # no tuner → exactly one untouched pread_into (the untuned path
    # stays byte-identical)
    r2 = Reader()
    fetch_windows(r2, "k", bytearray(10000), 0, None)
    assert r2.calls == [(0, 10000)] and r2.streams == 99


def test_tuned_pull_over_real_peer(tmp_path, monkeypatch):
    """End to end, dep-light: a tuned windowed fetch off a live native
    peer lands bytes-exact while the controller runs, and the telemetry
    plane records the pull rate the tuner read."""
    monkeypatch.setenv("DEMODEL_TUNER_TICK_MS", "50")
    from demodel_tpu.config import ProxyConfig
    from demodel_tpu.proxy import ProxyServer
    from demodel_tpu.sink.remote import PeerBlobReader
    from demodel_tpu.store import Store

    cfg = ProxyConfig(host="127.0.0.1", port=0, mitm_hosts=[],
                      no_mitm=True, cache_dir=tmp_path / "c",
                      data_dir=tmp_path / "d")
    store = Store(cfg.cache_dir / "proxy")
    rng = np.random.default_rng(3)
    body = rng.bytes(2 << 20)
    store.put("tunedobj00000001", body,
              {"content-type": "application/octet-stream"})
    store.close()
    node = ProxyServer(cfg, verbose=False).start()
    try:
        t = PullTuner(prefetch_depth=0, tick_s=0.05, window_s=2).start()
        try:
            t.window_bytes = 256 << 10  # force several windows
            reader = PeerBlobReader(node.url, "tunedobj00000001",
                                    len(body), streams=1)
            out = bytearray(len(body))
            fetch_windows(reader, "tunedobj00000001", out, 0, t)
            assert hashlib.sha256(out).hexdigest() == \
                hashlib.sha256(body).hexdigest()
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and \
                    m.HUB.get_gauge("tuner_throughput_bps") == 0:
                time.sleep(0.05)
        finally:
            t.stop()
        assert m.HUB.get("pull_bytes_total") == len(body)
        # several window-read spans → the windowed p99 the tuner reads
        name = m.labeled("stage_duration_seconds", span="window-read")
        h = m.HUB.get_histogram(name)
        assert h is not None and h.count >= 8
        assert m.HUB.get_gauge("tuner_throughput_bps") > 0
    finally:
        node.stop()


def test_snapshot_serializes_with_the_tick_thread():
    """Regression (PR 10, guarded-field finding): snapshot() must read
    under the SAME lock the tick thread writes under — a reader used to
    see decision N's count paired with decision N-1's knob values. The
    lock discipline is asserted deterministically: a held knob lock
    blocks snapshot() until released."""
    t = _tuner()
    done = threading.Event()
    out: dict = {}

    def read():
        out.update(t.snapshot())
        done.set()

    with t._knob_lock:  # noqa: SLF001 — the lock IS the contract under test
        reader = threading.Thread(target=read, daemon=True)
        reader.start()
        assert not done.wait(0.2), \
            "snapshot() completed while the knob lock was held"
    assert done.wait(2.0)
    reader.join(timeout=2)
    assert out["streams"] == t.streams
    assert out["window_mb"] == out["window_bytes"] >> 20


def test_snapshot_is_decision_consistent_under_concurrent_ticks():
    """Hammer forced ticks on one thread while snapshotting on another:
    every snapshot's decision count must agree with the knob state that
    decision produced (the torn read the knob lock exists to prevent).
    The writer keeps streams = min + (decisions % 2) as its invariant."""
    t = _tuner()
    t.min_streams = t.streams = 1
    t.max_streams = 2
    t.max_window = t.window_bytes      # pin: only the streams knob moves
    t.max_prefetch = t.prefetch_depth  # pin
    stop = threading.Event()

    def churn():
        while not stop.is_set():
            # alternate probe-up / revert: each is one decision moving
            # streams between 1 and 2 in lockstep with the count
            t.tick(thr=1000.0, retry_rate=0.0, breaker_open=False,
                   budget_wait_share=0.0)
            t.tick(thr=1.0, retry_rate=0.0, breaker_open=False,
                   budget_wait_share=0.0)

    w = threading.Thread(target=churn, daemon=True)
    w.start()
    try:
        for _ in range(400):
            snap = t.snapshot()
            assert snap["streams"] == 1 + (snap["decisions"] % 2), snap
    finally:
        stop.set()
        w.join(timeout=5)


def test_statusz_reads_tuner_knobs_via_snapshot(monkeypatch):
    """statusz's effective-config must take ONE consistent tuner
    snapshot, not per-attribute reads that can straddle a decision."""
    from demodel_tpu.sink.tuner import _register, _unregister
    from demodel_tpu.utils import statusz

    t = _tuner()
    _register(t)  # visible to statusz without a live tick thread
    try:
        calls = {"n": 0}
        real = t.snapshot

        def counted():
            calls["n"] += 1
            return real()

        monkeypatch.setattr(t, "snapshot", counted)
        cfg = statusz.effective_config()
        assert calls["n"] == 1, "effective_config must snapshot exactly once"
        assert cfg["DEMODEL_PEER_STREAMS"]["source"] == "tuner"
        assert cfg["DEMODEL_PEER_STREAMS"]["value"] == real()["streams"]
        assert cfg["DEMODEL_PULL_WINDOW_MB"]["value"] == real()["window_mb"]
    finally:
        _unregister(t)
