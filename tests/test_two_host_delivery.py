"""Two-process delivery proof (VERDICT r2 #3): two OS processes sharing one
jax.distributed 8-device mesh deliver a checkpoint; each host reads ONLY
its shards' bytes (the test FAILS if either host reads the full
checkpoint), replicated tensors complete over the mesh all-gather, and
cross-host fingerprints agree."""

import json
import socket
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from demodel_tpu.formats import safetensors as st
from demodel_tpu.store import Store


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture()
def checkpoint(tmp_path):
    """A store holding one blob: tp-shardable weights + a large replicated
    tensor (the ICI-completion target)."""
    rng = np.random.default_rng(0)
    tensors = {
        "blocks.0.w": rng.standard_normal((256, 128)).astype(np.float32),
        "blocks.1.w": rng.standard_normal((256, 128)).astype(np.float32),
        # plan replicates this (1-D can't shard on tp under the plan), and
        # it is big + row-divisible → the ici_complete staging kicks in
        "replicated.big": rng.standard_normal((512, 64)).astype(np.float32),
    }
    blob = st.serialize(tensors)
    root = tmp_path / "shared-store"
    s = Store(root)
    s.put("twohostckpt00001", blob, {})
    s.close()
    return root, "twohostckpt00001", tensors, blob


def _run_workers(root, key, mode):
    import os

    port = _free_port()
    worker = Path(__file__).parent / "two_host_worker.py"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    procs = [subprocess.Popen(
        [sys.executable, str(worker), str(i), str(port), str(root), key,
         mode],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
        for i in range(2)]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, f"worker failed:\n{out}\n{err[-3000:]}"
        outs.append(json.loads(out.strip().splitlines()[-1]))
    return outs


def test_two_processes_split_the_read_bytes(checkpoint):
    """tp mesh: every tensor shards; each host reads only its shards."""
    root, key, tensors, _ = checkpoint
    outs = _run_workers(root, key, "tp")
    total_weight_bytes = sum(a.nbytes for a in tensors.values())
    for o in outs:
        # THE core assertion: a host that read the full checkpoint fails
        assert o["bytes_read"] < total_weight_bytes, \
            f"host {o['pid']} read {o['bytes_read']} of " \
            f"{total_weight_bytes} — full-checkpoint read"
        assert o["bytes_read"] <= total_weight_bytes * 0.55
    # both hosts together read each byte exactly once
    assert sum(o["bytes_read"] for o in outs) == total_weight_bytes
    # cross-host placement fingerprints agree tensor-for-tensor
    assert outs[0]["fp"] == outs[1]["fp"]


def test_replicated_completion_over_collectives(checkpoint):
    """dp mesh (SURVEY §2.3 intra-pod shard exchange): every host needs
    FULL replicas, yet each reads only half the bytes — the mesh
    all-gather moves the other half. Fails if either host reads it all."""
    root, key, tensors, _ = checkpoint
    outs = _run_workers(root, key, "dp")
    total_weight_bytes = sum(a.nbytes for a in tensors.values())
    for o in outs:
        assert o["bytes_read"] < total_weight_bytes, \
            f"host {o['pid']} read everything — ICI completion inactive"
        assert o["bytes_read"] <= total_weight_bytes * 0.55
    assert sum(o["bytes_read"] for o in outs) == total_weight_bytes
    assert outs[0]["fp"] == outs[1]["fp"]
    # replicas are complete and source-exact on BOTH hosts
    want_sum = float(tensors["replicated.big"].astype(np.float64).sum())
    for o in outs:
        assert o["rep_shape"] == [512, 64]
        assert abs(o["rep_local_sum"] - want_sum) < 1e-6 * max(
            1.0, abs(want_sum))


def test_ici_complete_parity_single_process(checkpoint, mesh8):
    """The ici_complete staging path must be value-identical to the naive
    replicated load (single-process mechanics check)."""
    root, key, tensors, _ = checkpoint
    from demodel_tpu.sink.hbm import deliver_safetensors

    s = Store(root)
    try:
        naive = deliver_safetensors(s, key, mesh=mesh8, ici_complete=False)
        staged = deliver_safetensors(s, key, mesh=mesh8, ici_complete=True)
        for name in tensors:
            np.testing.assert_array_equal(np.asarray(naive.arrays[name]),
                                          np.asarray(staged.arrays[name]))
            assert (staged.arrays[name].sharding.spec
                    == naive.arrays[name].sharding.spec)
    finally:
        s.close()
