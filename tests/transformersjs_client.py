"""Client-faithful transformers.js fetch sequence (VERDICT r3 #8).

Reproduces the wire shape ``@huggingface/transformers`` (transformers.js)
produces when a BROWSER loads a model through the proxy
(`/root/reference/README.md:14-21` puts transformers.js in the client
matrix). Node is not in this image, so this mirrors the ollama approach:
a standalone subprocess emitting the exact request sequence the real
client's ``fetch`` calls generate:

- cross-origin + custom headers ⇒ the browser sends a CORS **preflight**
  (``OPTIONS`` + ``Origin`` + ``Access-Control-Request-Method/Headers``)
  before every distinct resource; the response must grant the origin or
  the real client never issues the GET;
- resource ``GET``\\ s carry ``Origin`` and must come back with
  ``Access-Control-Allow-Origin`` (the browser enforces it on the
  response too);
- weight files are also read **ranged** (the streaming/partial-read path)
  and revalidated with ``If-None-Match`` on the captured ``ETag`` (the
  browser Cache API's revalidation), accepting 304 or a full 200.

Proxying comes from the environment (HTTPS_PROXY + REQUESTS_CA_BUNDLE),
exactly like a browser behind a system proxy.

Usage: transformersjs_client.py <endpoint> <model> <dest>
Prints one JSON line.
"""

import json
import sys
from pathlib import Path

import requests

ORIGIN = "https://webml-demo.example"

FILES = ["config.json", "tokenizer.json", "tokenizer_config.json",
         "onnx/model.onnx"]


def preflight(sess: requests.Session, url: str, req_headers: str) -> dict:
    r = sess.options(url, headers={
        "Origin": ORIGIN,
        "Access-Control-Request-Method": "GET",
        "Access-Control-Request-Headers": req_headers,
    }, timeout=60)
    acao = r.headers.get("Access-Control-Allow-Origin", "")
    if r.status_code >= 400 or acao not in ("*", ORIGIN):
        raise SystemExit(f"preflight denied for {url}: {r.status_code} "
                         f"ACAO={acao!r}")
    return {"status": r.status_code, "acao": acao,
            "allow_headers": r.headers.get("Access-Control-Allow-Headers", "")}


def main() -> int:
    endpoint, model, dest = sys.argv[1], sys.argv[2], Path(sys.argv[3])
    dest.mkdir(parents=True, exist_ok=True)
    sess = requests.Session()
    out = {"files": {}, "preflights": 0, "etag_revalidated": 0}

    for name in FILES:
        url = f"{endpoint}/{model}/resolve/main/{name}"
        preflight(sess, url, "range")
        out["preflights"] += 1
        r = sess.get(url, headers={"Origin": ORIGIN}, timeout=300)
        r.raise_for_status()
        acao = r.headers.get("Access-Control-Allow-Origin", "")
        if acao not in ("*", ORIGIN):
            raise SystemExit(f"GET {name}: response lacks usable ACAO "
                             f"({acao!r}) — a browser would discard it")
        body = r.content
        p = dest / name.replace("/", "_")
        p.write_bytes(body)
        out["files"][name] = {"bytes": len(body),
                              "etag": r.headers.get("ETag", "")}

    # streaming/partial read of the weight file, still cross-origin
    wurl = f"{endpoint}/{model}/resolve/main/onnx/model.onnx"
    r = sess.get(wurl, headers={"Origin": ORIGIN, "Range": "bytes=0-1023"},
                 timeout=60)
    if r.status_code not in (200, 206):
        raise SystemExit(f"ranged weight read failed: {r.status_code}")
    out["ranged_status"] = r.status_code
    out["ranged_acao"] = r.headers.get("Access-Control-Allow-Origin", "")

    # Cache-API revalidation on the captured ETag
    for name in FILES:
        etag = out["files"][name]["etag"]
        if not etag:
            continue
        url = f"{endpoint}/{model}/resolve/main/{name}"
        r = sess.get(url, headers={"Origin": ORIGIN,
                                   "If-None-Match": etag}, timeout=60)
        if r.status_code == 304 or (r.status_code == 200 and
                                    r.headers.get("ETag", "") == etag):
            out["etag_revalidated"] += 1

    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
