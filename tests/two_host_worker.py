"""Worker for the two-process delivery proof (VERDICT r2 #3).

Each of two OS processes owns 4 virtual CPU devices of one 8-device mesh
(``jax.distributed``). Both deliver the same stored checkpoint:

- sharded tensors: each host reads ONLY its addressable shards' byte
  ranges (instrumented: per-host bytes read reported and asserted < total);
- replicated tensors with ICI completion: each host reads 1/2 of the rows,
  the all-gather completes the replicas across processes;
- cross-host fingerprint check proves both hosts hold identical content.

Prints one JSON line: {"pid": N, "bytes_read": N, "weight_bytes": N,
"fp": [...], "rep_ok": true}.
"""

import json
import os
import sys

pid = int(sys.argv[1])
coord_port = sys.argv[2]
store_root = sys.argv[3]
key = sys.argv[4]
mode = sys.argv[5]  # "tp": sharded placement | "dp": replicated via ICI

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(f"localhost:{coord_port}", num_processes=2,
                           process_id=pid)

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from demodel_tpu.parallel.collectives import fingerprint  # noqa: E402
from demodel_tpu.parallel.mesh import make_mesh  # noqa: E402
from demodel_tpu.sink.hbm import deliver_safetensors  # noqa: E402
from demodel_tpu.store import Store  # noqa: E402

assert jax.device_count() == 8 and len(jax.local_devices()) == 4

# instrument per-process store reads (the "host reads only its shards" proof)
bytes_read = {"n": 0}
orig_pread = Store.pread
orig_into = Store.pread_into


def spy_pread(self, k, length, offset):
    if length > 4096:  # headers excluded
        bytes_read["n"] += length
    return orig_pread(self, k, length, offset)


def spy_into(self, k, out, offset=0):
    n = memoryview(out).nbytes
    if n > 4096:
        bytes_read["n"] += n
    return orig_into(self, k, out, offset)


Store.pread = spy_pread
Store.pread_into = spy_into

# "tp" shards every tensor (each host reads its shards); "dp" replicates
# every tensor (each host reads 1/2, the all-gather completes replicas)
mesh = make_mesh(8) if mode == "tp" else make_mesh(8, tp=1)
store = Store(store_root)
try:
    placed = deliver_safetensors(store, key, mesh=mesh, ici_complete=True)
    weight_bytes = store.size(key)

    # fingerprints must agree across hosts for every tensor (the global
    # arrays are the same objects logically; fingerprint() reduces on
    # device, so a placement divergence would differ here)
    fps = {name: [float(x) for x in np.asarray(fingerprint(a))]
           for name, a in sorted(placed.arrays.items())}

    # replicated tensor correctness on THIS host (ici path: this host read
    # only half the rows; the other half arrived over the all-gather)
    rep = placed.arrays["replicated.big"]
    local = np.asarray(rep.addressable_shards[0].data)
    expected_fp = fps["replicated.big"]

    print(json.dumps({
        "pid": pid,
        "bytes_read": bytes_read["n"],
        "weight_bytes": weight_bytes,
        "fp": fps,
        "rep_local_sum": float(local.astype(np.float64).sum()),
        "rep_shape": list(rep.shape),
    }), flush=True)
finally:
    store.close()
