"""Client-faithful vLLM cold-start (BASELINE config 4; VERDICT r3 #5).

Reproduces the exact wire sequence vLLM's default model loader performs
when cold-starting from the HF Hub through ``HTTPS_PROXY``
(`/root/reference/README.md:16-19` names vLLM/SGLang in the client
matrix):

1. ``GET /api/models/{repo}/revision/{rev}`` — sibling listing (what
   ``huggingface_hub.snapshot_download`` resolves first);
2. small files (config/tokenizer/index) via plain ``GET /resolve``;
3. every ``.safetensors`` shard the **hf_transfer way**: resolve the CDN
   redirect once, then N parallel ranged ``GET``\\ s of ~chunk-sized
   windows — the multi-connection ranged-read shape that hammers a cold
   proxy cache (ranged-miss fill) and a warm one (range-from-cache);
4. parse the shards and ``device_put`` every tensor — the load "ends in
   HBM" exactly like vLLM's weight loading step.

Proxying comes entirely from the environment (HTTPS_PROXY +
REQUESTS_CA_BUNDLE), as with the real client.

Usage: vllm_load_client.py <endpoint> <model> <dest> [chunk_mb] [workers]
Prints one JSON line with timings/bytes/fingerprints.
"""

import json
import os
import sys
import time
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path

import requests

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def fetch_ranged(sess: requests.Session, url: str, size: int, dest: Path,
                 chunk_bytes: int, workers: int) -> int:
    """hf_transfer-shaped download: pre-size the file, fan ranged GETs of
    ``chunk_bytes`` windows over a thread pool. Returns request count."""
    dest.parent.mkdir(parents=True, exist_ok=True)
    with open(dest, "wb") as f:
        f.truncate(size)
    ranges = [(off, min(size, off + chunk_bytes) - 1)
              for off in range(0, size, chunk_bytes)]

    def one(rng):
        a, b = rng
        r = sess.get(url, headers={"Range": f"bytes={a}-{b}"}, timeout=300)
        r.raise_for_status()
        if r.status_code != 206:
            raise RuntimeError(f"expected 206 for {a}-{b}, got {r.status_code}")
        body = r.content
        if len(body) != b - a + 1:
            raise RuntimeError(f"short range body: {len(body)}")
        with open(dest, "r+b") as f:
            f.seek(a)
            f.write(body)
        return 1

    with ThreadPoolExecutor(max_workers=workers) as ex:
        return sum(ex.map(one, ranges))


def main() -> int:
    endpoint, model, dest = sys.argv[1], sys.argv[2], Path(sys.argv[3])
    chunk_mb = int(sys.argv[4]) if len(sys.argv) > 4 else 10
    workers = int(sys.argv[5]) if len(sys.argv) > 5 else 8
    sess = requests.Session()

    t0 = time.perf_counter()
    info = sess.get(f"{endpoint}/api/models/{model}/revision/main",
                    timeout=60)
    info.raise_for_status()
    siblings = [s["rfilename"] for s in info.json()["siblings"]]

    small = [n for n in siblings if not n.endswith(".safetensors")]
    shards = [n for n in siblings if n.endswith(".safetensors")]
    for name in small:
        r = sess.get(f"{endpoint}/{model}/resolve/main/{name}", timeout=60)
        r.raise_for_status()
        p = dest / name
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_bytes(r.content)

    range_requests = 0
    total_bytes = 0
    for name in shards:
        # resolve once (redirect to CDN), then ranged fan-out on the final
        # URL — hf_transfer receives the resolved URL from huggingface_hub
        h = sess.get(f"{endpoint}/{model}/resolve/main/{name}",
                     headers={"Range": "bytes=0-0"}, timeout=60)
        h.raise_for_status()
        size = int(h.headers["Content-Range"].rpartition("/")[2])
        final_url = h.url
        range_requests += fetch_ranged(sess, final_url, size, dest / name,
                                       chunk_mb << 20, workers)
        total_bytes += size
    download_secs = time.perf_counter() - t0

    # ---- vLLM's weight-loading step: parse + device_put (→ HBM)
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from demodel_tpu.formats import safetensors as st

    arrays = {}
    for name in shards:
        blob = (dest / name).read_bytes()
        idx = st.parse_header(blob)
        for tname, spec in idx.tensors.items():
            arrays[tname] = jax.device_put(
                spec.to_numpy(blob[spec.start:spec.end]))
    jax.block_until_ready(list(arrays.values()))
    load_secs = time.perf_counter() - t0 - download_secs

    fp = {n: float(np.asarray(a, dtype=np.float64).sum())
          for n, a in sorted(arrays.items())}
    print(json.dumps({
        "download_secs": round(download_secs, 3),
        "load_secs": round(load_secs, 3),
        "total_secs": round(download_secs + load_secs, 3),
        "bytes": total_bytes,
        "range_requests": range_requests,
        "tensors": len(arrays),
        "fp": fp,
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
