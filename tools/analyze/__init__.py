"""Repo-native static analysis for the demodel-tpu tree.

A small pass framework (``python -m tools.analyze``) that walks Python
sources with :mod:`ast` and runs pluggable rule passes tuned to this
stack's failure modes: host↔device syncs on delivery hot paths, blocking
I/O under locks, swallowed exceptions in failover paths, jit tracing
hazards, module-level lock-order cycles, eager log formatting, and
unguarded JSON shape access on peer responses.

Findings print as ``file:line rule-id message`` and are suppressible
inline with ``# demodel: allow(<rule-id>)`` on the offending line or the
line above. See ``tools/analyze/README.md`` for the rule catalogue and
how to add a pass.
"""

from tools.analyze.core import (  # noqa: F401 — public surface
    Finding,
    ModuleContext,
    Pass,
    REGISTRY,
    analyze_paths,
    register,
)
