"""CLI driver: ``python -m tools.analyze [paths...]``.

Exit code 0 when the tree has no unsuppressed findings, 1 otherwise —
what tier-1 (tests/test_static_analysis.py) and CI gate on.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from tools.analyze.core import REGISTRY, analyze_paths


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.analyze",
        description="demodel-tpu static analysis passes",
    )
    ap.add_argument("paths", nargs="*", default=["demodel_tpu"],
                    help="files/directories to analyze (default: demodel_tpu)")
    ap.add_argument("--rule", action="append", default=[],
                    help="run only this rule id (repeatable)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalogue and exit")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as JSON")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print suppressed findings (marked)")
    args = ap.parse_args(argv)

    if args.list_rules:
        import tools.analyze.passes  # noqa: F401 — populate REGISTRY

        for rule_id in sorted(REGISTRY):
            print(f"{rule_id}: {REGISTRY[rule_id].description}")
        return 0

    paths = [Path(p) for p in (args.paths or ["demodel_tpu"])]
    missing = [p for p in paths if not p.exists()]
    if missing:
        print(f"no such path: {', '.join(map(str, missing))}", file=sys.stderr)
        return 2
    active, suppressed = analyze_paths(paths, rule_ids=args.rule or None)

    if args.as_json:
        print(json.dumps({
            "findings": [vars(f) for f in active],
            "suppressed": [vars(f) for f in suppressed],
        }, indent=2))
    else:
        for f in active:
            print(f.render())
        if args.show_suppressed:
            for f in suppressed:
                print(f"{f.render()}  [suppressed]")
        tail = f"{len(active)} finding(s), {len(suppressed)} suppressed"
        print(tail, file=sys.stderr)
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
