"""CLI driver: ``python -m tools.analyze [paths...]``.

Exit code 0 when the tree has no unsuppressed findings, 1 otherwise —
what tier-1 (tests/test_static_analysis.py) and CI gate on.

Modes on top of the plain run:

- ``--json`` / ``--sarif PATH`` — machine-readable findings (SARIF is
  what CI uploads so findings annotate PRs; ``-`` writes to stdout);
- ``--changed-only`` — report only files touched per ``git status``;
  the ProjectIndex still spans every analyzed file, so cross-module
  findings in a changed file keep firing;
- ``--stats`` — per-rule finding/suppression counts and files/s;
- ``--check-suppressions`` — every inline ``# demodel: allow(rule)``
  must carry a justification (text after the allow), and every pragma
  must still be EARNING its keep: an allow whose rule no longer fires
  on any line it covers is stale and fails the run (dead pragmas
  silently bless future regressions); only rules that actually ran are
  audited, so ``--rule`` subsets never produce false staleness;
- results are cached (``.demodel-analyze-cache.json``) keyed on every
  analyzed file's (path, mtime, size) plus the analyzer's own sources —
  ``--no-cache`` forces a cold run.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
import time
from pathlib import Path

from tools.analyze.core import (
    REGISTRY,
    SUPPRESS_RE,
    analyze_paths,
    iter_py_files,
)


def _changed_files(root: Path) -> set[str] | None:
    """Repo-relative posix paths touched per git (staged, unstaged,
    untracked), or None when git is unavailable."""
    try:
        # -uall: list files inside untracked directories individually
        # (default -unormal collapses them to one "dir/" entry, which
        # would silently drop every finding in a newly added package)
        out = subprocess.run(
            ["git", "status", "--porcelain", "--no-renames", "-uall"],
            cwd=root, capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if out.returncode != 0:
        return None
    changed: set[str] = set()
    for line in out.stdout.splitlines():
        if len(line) > 3:
            changed.add(line[3:].strip().strip('"'))
    return changed


def check_suppressions(files) -> list[str]:
    """Inline allows lacking a justification: every
    ``# demodel: allow(rule)`` must be followed by reason text (same
    line after the paren, or the continuation of a comment block)."""
    bad: list[str] = []
    for path in files:
        try:
            lines = Path(path).read_text(
                encoding="utf-8", errors="replace").splitlines()
        except OSError:
            continue
        for i, line in enumerate(lines, start=1):
            m = SUPPRESS_RE.search(line)
            if not m:
                continue
            if m.start() > 0 and line[m.start() - 1] == "`":
                continue  # doc MENTION of the grammar, not a pragma
            reason = line[m.end():].strip().strip("—-–: ").strip()
            # comment-block form: the justification may span the
            # following comment-only lines — accumulate them all, so a
            # short first continuation ("# why:") doesn't mask real text
            # further down the block
            j = i
            while j < len(lines) and lines[j].strip().startswith("#"):
                reason += " " + lines[j].strip().lstrip("#").strip("—-–: ")
                j += 1
            if len(reason.strip()) < 8:
                bad.append(f"{path}:{i} allow({m.group(1)}) carries no "
                           "justification — say why this pattern is "
                           "deliberate")
    return bad


def stale_suppressions(files, suppressed, run_rules, root) -> list[str]:
    """Inline allows whose rule no longer fires on any line they cover.

    A pragma proves its worth by appearing in the suppressed-findings
    list; one that suppresses nothing is a hole waiting for a real
    finding to fall through. Coverage mirrors ``core.suppressions`` /
    ``core.is_suppressed`` exactly: the pragma's own line (plus the
    comment-block extension for comment-only allows), matched against
    each finding's line and the line above it. Pragmas none of whose
    rules were run this invocation are skipped — absence of findings
    means nothing for a rule that never looked.
    """
    by_path: dict[str, list] = {}
    for f in suppressed:
        by_path.setdefault(f.path, []).append(f)
    run = set(run_rules)
    out: list[str] = []
    for path in files:
        p = Path(path)
        try:
            rel = p.resolve().relative_to(Path(root).resolve()).as_posix()
        except ValueError:
            rel = p.as_posix()
        try:
            lines = p.read_text(
                encoding="utf-8", errors="replace").splitlines()
        except OSError:
            continue
        hits = by_path.get(rel, [])
        for i, line in enumerate(lines, start=1):
            m = SUPPRESS_RE.search(line)
            if not m:
                continue
            if m.start() > 0 and line[m.start() - 1] == "`":
                continue  # backtick-quoted doc mention, not a pragma
            ids = {t.strip() for t in m.group(1).split(",") if t.strip()}
            ids = ids or {"*"}
            if "*" not in ids and not (ids & run):
                continue
            cov = {i}
            if line.strip().startswith(("#", "/")):
                j = i + 1
                while j <= len(lines) and (
                        not lines[j - 1].strip()
                        or lines[j - 1].strip().startswith("#")):
                    cov.add(j)
                    j += 1
            live = any(
                (f.line in cov or f.line - 1 in cov)
                and ("*" in ids or f.rule in ids)
                for f in hits)
            if not live:
                out.append(
                    f"{rel}:{i} allow({m.group(1)}) is stale — the rule "
                    "no longer fires on the lines it covers; remove the "
                    "pragma so a future regression cannot hide under it")
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.analyze",
        description="demodel-tpu static analysis passes",
    )
    ap.add_argument("paths", nargs="*", default=["demodel_tpu"],
                    help="files/directories to analyze (default: demodel_tpu)")
    ap.add_argument("--rule", action="append", default=[],
                    help="run only this rule id (repeatable)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalogue and exit")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as JSON")
    ap.add_argument("--sarif", metavar="PATH",
                    help="write findings as SARIF 2.1.0 to PATH ('-' = stdout)")
    ap.add_argument("--changed-only", action="store_true",
                    help="report only git-changed files (index stays "
                         "whole-program)")
    ap.add_argument("--no-cache", action="store_true",
                    help="ignore and do not update the result cache")
    ap.add_argument("--stats", action="store_true",
                    help="print per-rule counts and files/s to stderr")
    ap.add_argument("--check-suppressions", action="store_true",
                    help="fail when an inline allow() carries no reason text")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print suppressed findings (marked)")
    args = ap.parse_args(argv)

    if args.list_rules:
        import tools.analyze.passes  # noqa: F401 — populate REGISTRY

        for rule_id in sorted(REGISTRY):
            print(f"{rule_id}: {REGISTRY[rule_id].description}")
        return 0

    paths = [Path(p) for p in (args.paths or ["demodel_tpu"])]
    missing = [p for p in paths if not p.exists()]
    if missing:
        print(f"no such path: {', '.join(map(str, missing))}", file=sys.stderr)
        return 2
    root = Path.cwd()
    files = iter_py_files(paths)

    report_only: set[str] | None = None
    if args.changed_only:
        changed = _changed_files(root)
        if changed is None:
            print("warning: git unavailable; --changed-only analyzing "
                  "everything", file=sys.stderr)
        else:
            rels = set()
            for p in files:
                try:
                    rels.add(p.resolve().relative_to(
                        root.resolve()).as_posix())
                except ValueError:
                    rels.add(p.as_posix())
            report_only = rels & changed

    t0 = time.perf_counter()
    cache_state = "off"
    sort_key = lambda f: (f.path, f.line, f.rule)  # noqa: E731
    if args.no_cache:
        active, suppressed = analyze_paths(
            paths, rule_ids=args.rule or None, report_only=report_only)
    else:
        import tools.analyze.passes  # noqa: F401 — rule→module mapping
        from tools.analyze import cache

        rule_ids = sorted(args.rule) if args.rule else sorted(REGISTRY)
        want = rule_ids + [cache.PARSE_RULE]
        keys = {rid: cache.rule_key(files, rid, report_only)
                for rid in want}
        hits: dict[str, tuple[list, list]] = {}
        missing: list[str] = []
        for rid in want:
            got = cache.load_rule(root, keys[rid])
            (hits.__setitem__(rid, got) if got is not None
             else missing.append(rid))
        if missing:
            # one analysis run covers every missed rule (the index is
            # built once); parse errors come free with any run
            run_rules = [r for r in missing if r != cache.PARSE_RULE] \
                or rule_ids
            run_a, run_s = analyze_paths(
                paths, rule_ids=run_rules, report_only=report_only)
            fresh: dict[str, tuple[list, list]] = {
                rid: ([], []) for rid in
                set(missing) | set(run_rules) | {cache.PARSE_RULE}}
            for bucket, found in ((0, run_a), (1, run_s)):
                for f in found:
                    rid = cache.PARSE_RULE if f.rule == "parse-error" \
                        else f.rule
                    if rid in fresh:
                        fresh[rid][bucket].append(f)
            cache.store_rules(root, {
                keys[rid]: (rid, a, s)
                for rid, (a, s) in fresh.items() if rid in keys})
            for rid in missing:
                hits[rid] = fresh.get(rid, ([], []))
        cache_state = ("hit" if not missing
                       else "miss" if len(missing) == len(want)
                       else f"partial ({len(want) - len(missing)}"
                            f"/{len(want)})")
        active = sorted((f for a, _ in hits.values() for f in a),
                        key=sort_key)
        suppressed = sorted((f for _, s in hits.values() for f in s),
                            key=sort_key)
    secs = time.perf_counter() - t0

    bad_sup: list[str] = []
    if args.check_suppressions:
        audit = list(files)
        native_dir = root / "native"
        if native_dir.is_dir():
            # // demodel: allow(...) pragmas live in the native plane
            # too; audit them alongside the Python ones
            audit += sorted(native_dir.glob("*.h"))
            audit += sorted(native_dir.glob("*.cc"))
        bad_sup = check_suppressions(audit)
        if report_only is None:
            # staleness needs the FULL suppressed list: under
            # --changed-only the filtered view would flag every pragma
            # in an untouched file
            import tools.analyze.passes  # noqa: F401 — populate REGISTRY

            run_rules = set(args.rule) if args.rule else set(REGISTRY)
            bad_sup += stale_suppressions(
                audit, suppressed, run_rules, root)
        for b in bad_sup:
            print(b, file=sys.stderr)

    if args.sarif:
        import tools.analyze.passes  # noqa: F401 — populate REGISTRY
        from tools.analyze.sarif import to_sarif

        doc = json.dumps(to_sarif(active, suppressed, REGISTRY), indent=2)
        if args.sarif == "-":
            print(doc)
        else:
            Path(args.sarif).write_text(doc + "\n")
    if args.as_json:
        print(json.dumps({
            "findings": [vars(f) for f in active],
            "suppressed": [vars(f) for f in suppressed],
        }, indent=2))
    elif args.sarif != "-":  # SARIF-to-stdout owns stdout
        for f in active:
            print(f.render())
        if args.show_suppressed:
            for f in suppressed:
                print(f"{f.render()}  [suppressed]")
        tail = f"{len(active)} finding(s), {len(suppressed)} suppressed"
        print(tail, file=sys.stderr)

    if args.stats:
        import tools.analyze.passes  # noqa: F401 — populate REGISTRY

        per_rule: dict[str, list[int]] = {}
        for f in active:
            per_rule.setdefault(f.rule, [0, 0])[0] += 1
        for f in suppressed:
            per_rule.setdefault(f.rule, [0, 0])[1] += 1
        print("— stats —", file=sys.stderr)
        for rid in sorted(set(REGISTRY) | set(per_rule)):
            a, s = per_rule.get(rid, (0, 0))
            print(f"  {rid}: {a} finding(s), {s} suppressed",
                  file=sys.stderr)
        rate = len(files) / secs if secs > 0 else float("inf")
        print(f"  files: {len(files)}  secs: {secs:.3f}  "
              f"files/s: {rate:.0f}  cache: {cache_state}",
              file=sys.stderr)

    return 1 if (active or bad_sup) else 0


if __name__ == "__main__":
    sys.exit(main())
