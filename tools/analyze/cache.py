"""Result cache: sub-second warm runs for the tier-1 analyze gate.

The unit of caching is the WHOLE run, keyed by every input that can
change its output: the (path, mtime, size) triple of every analyzed
file, the analyzer's own sources (same triples — editing a pass
invalidates), the rule selection, and the report filter. Any change
recomputes everything; a hit replays the stored findings. That makes the
cache trivially sound for interprocedural rules — a per-file cache would
have to reason about which summaries a cross-module edit invalidates,
and a wrong answer there silently hides findings.

The store is a small JSON file at the repo root
(``.demodel-analyze-cache.json``, gitignored), capped at a handful of
entries (LRU) so switching between ``demodel_tpu`` and fixture runs does
not thrash.
"""

from __future__ import annotations

import hashlib
import json
import os
from pathlib import Path

from tools.analyze.core import Finding

CACHE_NAME = ".demodel-analyze-cache.json"
MAX_ENTRIES = 6
_TOOL_DIR = Path(__file__).resolve().parent


def _stat_triples(files) -> list:
    out = []
    for p in files:
        try:
            st = os.stat(p)
        except OSError:
            out.append((str(p), 0, -1))
            continue
        out.append((str(p), st.st_mtime_ns, st.st_size))
    return out


def run_key(files, rule_ids, report_only) -> str:
    """Digest of everything that determines a run's findings."""
    tool_files = sorted(_TOOL_DIR.rglob("*.py"))
    payload = {
        "files": _stat_triples(files),
        "tool": _stat_triples(tool_files),
        "rules": sorted(rule_ids) if rule_ids else None,
        # None (no filter) and set() (filter matching nothing) are
        # different runs with different outputs — must not share a key
        "report_only": sorted(report_only) if report_only is not None
        else None,
    }
    blob = json.dumps(payload, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()


def _cache_path(root: Path) -> Path:
    return Path(root) / CACHE_NAME


def load(root: Path, key: str):
    """``(active, suppressed)`` lists for ``key``, or None on miss."""
    try:
        data = json.loads(_cache_path(root).read_text())
    except (OSError, ValueError):
        return None
    for entry in data.get("entries", []):
        if entry.get("key") == key:
            try:
                return (
                    [Finding(**f) for f in entry["active"]],
                    [Finding(**f) for f in entry["suppressed"]],
                )
            except (KeyError, TypeError):
                return None
    return None


def store(root: Path, key: str, active, suppressed) -> None:
    path = _cache_path(root)
    try:
        data = json.loads(path.read_text())
        entries = [e for e in data.get("entries", [])
                   if e.get("key") != key]
    except (OSError, ValueError):
        entries = []
    entries.append({
        "key": key,
        "active": [vars(f) for f in active],
        "suppressed": [vars(f) for f in suppressed],
    })
    entries = entries[-MAX_ENTRIES:]
    try:
        tmp = path.with_suffix(".tmp")
        tmp.write_text(json.dumps({"entries": entries}))
        tmp.replace(path)
    except OSError:
        pass  # a read-only checkout just runs cold every time
