"""Result cache: sub-second warm runs for the tier-1 analyze gate.

The unit of caching is one RULE's findings over one input set. Each
rule's key digests every input that can change its output:

- the (path, mtime, size) triple of every analyzed file — any source
  edit invalidates every rule (a cross-module edit can change any
  interprocedural finding, and a per-file cache that tried to be
  smarter would have to reason about summary invalidation, where a
  wrong answer silently hides findings);
- the SHARED analyzer framework sources (core/index/driver/cache/
  sarif) — framework edits invalidate everything;
- the rule's OWN pass module (path, mtime, size) plus its declared
  ``version`` string — editing one pass re-runs only that pass, so a
  rule-development loop pays one rule's cost, not sixteen;
- the report filter (``--changed-only``).

Parse errors are file-level, not rule-level — they live under the
pseudo-rule ``__parse__`` keyed on the framework sources.

The store is a small JSON file at the repo root
(``.demodel-analyze-cache.json``, gitignored), LRU-capped so switching
between ``demodel_tpu`` and fixture runs does not thrash.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
from pathlib import Path

from tools.analyze.core import Finding

CACHE_NAME = ".demodel-analyze-cache.json"
#: rules × a few distinct path-sets
MAX_ENTRIES = 96
_TOOL_DIR = Path(__file__).resolve().parent

#: the pseudo-rule holding file-level parse errors
PARSE_RULE = "__parse__"

#: framework sources shared by every rule — an edit here invalidates
#: the whole cache (passes/__init__.py included: it defines the
#: registration set itself)
_SHARED = [
    _TOOL_DIR / "core.py",
    _TOOL_DIR / "index.py",
    _TOOL_DIR / "obligations.py",
    _TOOL_DIR / "native_index.py",
    _TOOL_DIR / "native_concurrency.py",
    _TOOL_DIR / "cache.py",
    _TOOL_DIR / "sarif.py",
    _TOOL_DIR / "__main__.py",
    _TOOL_DIR / "__init__.py",
    _TOOL_DIR / "passes" / "__init__.py",
]


def _stat_triples(files) -> list:
    out = []
    for p in files:
        try:
            st = os.stat(p)
        except OSError:
            out.append((str(p), 0, -1))
            continue
        out.append((str(p), st.st_mtime_ns, st.st_size))
    return out


def _pass_source(rule_id: str) -> tuple[Path | None, str]:
    """(pass module file, rule version) for one registered rule."""
    from tools.analyze.core import REGISTRY

    cls = REGISTRY.get(rule_id)
    if cls is None:
        return None, ""
    mod = sys.modules.get(cls.__module__)
    f = getattr(mod, "__file__", None)
    return (Path(f) if f else None), str(getattr(cls, "version", "1"))


def rule_key(files, rule_id: str, report_only) -> str:
    """Digest of everything that determines ONE rule's findings —
    including any NON-Python inputs the pass declares (surface-parity's
    native tree: a rank edit in lock_order.h must invalidate its
    entry, or the warm gate silently blesses drift)."""
    from tools.analyze.core import REGISTRY

    own, version = _pass_source(rule_id)
    cls = REGISTRY.get(rule_id)
    extra = cls.cache_extra_inputs(files) if cls is not None else []
    payload = {
        "rule": rule_id,
        "version": version,
        "files": _stat_triples(files),
        "shared": _stat_triples(_SHARED),
        "own": _stat_triples([own] if own is not None else []),
        "extra": _stat_triples(extra),
        # None (no filter) and set() (filter matching nothing) are
        # different runs with different outputs — must not share a key
        "report_only": sorted(report_only) if report_only is not None
        else None,
    }
    blob = json.dumps(payload, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()


def _cache_path(root: Path) -> Path:
    return Path(root) / CACHE_NAME


def _read(root: Path) -> list:
    try:
        data = json.loads(_cache_path(root).read_text())
    except (OSError, ValueError):
        return []
    entries = data.get("entries", [])
    return entries if isinstance(entries, list) else []


def load_rule(root: Path, key: str):
    """``(active, suppressed)`` for one rule key, or None on miss."""
    for entry in _read(root):
        if entry.get("key") == key:
            try:
                return (
                    [Finding(**f) for f in entry["active"]],
                    [Finding(**f) for f in entry["suppressed"]],
                )
            except (KeyError, TypeError):
                return None
    return None


def store_rules(root: Path, results: dict) -> None:
    """Persist ``{key: (rule, active, suppressed)}`` entries (LRU)."""
    path = _cache_path(root)
    entries = [e for e in _read(root) if e.get("key") not in results]
    for key, (rule, active, suppressed) in results.items():
        entries.append({
            "key": key,
            "rule": rule,
            "active": [vars(f) for f in active],
            "suppressed": [vars(f) for f in suppressed],
        })
    entries = entries[-MAX_ENTRIES:]
    try:
        tmp = path.with_suffix(".tmp")
        tmp.write_text(json.dumps({"entries": entries}))
        tmp.replace(path)
    except OSError:
        pass  # a read-only checkout just runs cold every time
