"""Pass framework: module contexts, the rule registry, suppression, driver.

Design notes
------------
- One :class:`ModuleContext` per file: parsed tree with parent links, raw
  source lines, and a ``hot`` bit (delivery hot-path modules, where the
  host-sync rule applies).
- A :class:`Pass` sees every module via :meth:`Pass.visit` and may emit
  more findings from :meth:`Pass.finalize` after the whole walk (the
  lock-order pass builds its graph that way).
- Suppression is inline and rule-scoped: ``# demodel: allow(rule-id)``
  (comma-separated ids, or ``*``) on the finding's line or the line
  directly above. Suppressed findings are still collected so tests can
  assert the suppression machinery works.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from pathlib import Path
from typing import Iterable, Iterator

SUPPRESS_RE = re.compile(r"(?:#|//)\s*demodel:\s*allow\(([^)]*)\)")
HOT_PRAGMA_RE = re.compile(r"#\s*demodel:\s*hot-path")

#: delivery hot-path packages — the host-sync rule applies only here (plus
#: any file carrying an explicit ``# demodel: hot-path`` pragma, which is
#: how the golden fixtures opt in)
HOT_DIRS = ("demodel_tpu/ops", "demodel_tpu/sink", "demodel_tpu/parallel")


@dataclass(frozen=True)
class Finding:
    path: str  # repo-relative posix path
    line: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line} {self.rule} {self.message}"


class ModuleContext:
    """One parsed source file plus the per-file facts passes need."""

    def __init__(self, path: Path, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=rel)
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                child._dm_parent = node  # type: ignore[attr-defined]
        self.module = rel[:-3].replace("/", ".") if rel.endswith(".py") else rel
        self.hot = (
            any(rel.startswith(d + "/") or rel == d for d in HOT_DIRS)
            or HOT_PRAGMA_RE.search(source) is not None
        )

    def src(self, node: ast.AST) -> str:
        """Best-effort source text of ``node`` (for messages/matching).

        Reimplements ``ast.get_source_segment`` over the pre-split line
        list: the stdlib version re-splits the whole file on every call,
        which profiled as ~80% of a full-tree run."""
        lineno = getattr(node, "lineno", None)
        end_lineno = getattr(node, "end_lineno", None)
        col = getattr(node, "col_offset", None)
        end_col = getattr(node, "end_col_offset", None)
        if None in (lineno, end_lineno, col, end_col):
            try:
                return ast.unparse(node)
            except Exception:  # pragma: no cover - unparse of odd nodes
                return "<expr>"
        if lineno == end_lineno:
            return self.lines[lineno - 1][col:end_col]
        first = self.lines[lineno - 1][col:]
        mid = self.lines[lineno:end_lineno - 1]
        last = self.lines[end_lineno - 1][:end_col]
        return "\n".join([first, *mid, last])


class Pass:
    """Base class for rule passes. Subclass, set ``id``/``description``,
    implement :meth:`visit` (and :meth:`finalize` for whole-project
    rules), then :func:`register` it and import the module from
    ``tools.analyze.passes``.

    Before any :meth:`visit`, the driver calls :meth:`begin` with the
    :class:`~tools.analyze.index.ProjectIndex` built over every module in
    the run — interprocedural rules read summaries and the call graph
    from ``self.index``."""

    id = ""
    description = ""
    #: bumped when a rule's SEMANTICS change without its module's source
    #: changing (e.g. behavior keyed on data files) — part of the
    #: per-rule cache key alongside the pass module's (mtime, size)
    version = "1"

    @classmethod
    def cache_extra_inputs(cls, files) -> list:
        """Extra files (beyond the analyzed ``.py`` set) whose content
        determines this rule's findings — their (path, mtime, size)
        triples join the rule's cache key. A pass that reads anything
        off-tree (surface-parity's native extractor) MUST declare it
        here, or a warm cache silently hides findings when only that
        input changes."""
        return []

    def __init__(self) -> None:
        self.index = None  # ProjectIndex, set by the driver via begin()

    def begin(self, index) -> None:
        self.index = index

    def visit(self, ctx: ModuleContext) -> Iterator[Finding]:
        return iter(())

    def finalize(self) -> Iterator[Finding]:
        return iter(())


REGISTRY: dict[str, type[Pass]] = {}


def register(cls: type[Pass]) -> type[Pass]:
    if not cls.id:
        raise ValueError(f"pass {cls.__name__} has no id")
    if cls.id in REGISTRY:
        raise ValueError(f"duplicate pass id {cls.id}")
    REGISTRY[cls.id] = cls
    return cls


# --------------------------------------------------------------- helpers


def dotted(node: ast.AST) -> str | None:
    """``a.b.c`` for Name/Attribute chains, else None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def walk_in_scope(node: ast.AST) -> Iterator[ast.AST]:
    """Walk ``node``'s body without descending into nested function or
    class definitions (their bodies run in a different dynamic context —
    e.g. code inside a nested ``def`` does not execute under the
    enclosing ``with``)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                          ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def enclosing_function(node: ast.AST) -> ast.AST | None:
    cur = getattr(node, "_dm_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = getattr(cur, "_dm_parent", None)
    return None


def enclosing_class(node: ast.AST) -> ast.ClassDef | None:
    cur = getattr(node, "_dm_parent", None)
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return cur
        cur = getattr(cur, "_dm_parent", None)
    return None


# ----------------------------------------------------------- suppression


def suppressions(source: str) -> dict[int, set[str]]:
    """1-based line number → rule ids allowed on that line (``*`` = all).

    An inline allow applies to its own line (and the next, so a trailing
    comment can cover a continuation). An allow on a comment-only line
    covers the whole comment block plus the first code line after it —
    justification lines between the allow and the code are encouraged.
    """
    out: dict[int, set[str]] = {}
    lines = source.splitlines()

    def add(line_no: int, ids: set[str]) -> None:
        out.setdefault(line_no, set()).update(ids)

    for i, line in enumerate(lines, start=1):
        m = SUPPRESS_RE.search(line)
        if not m:
            continue
        ids = {tok.strip() for tok in m.group(1).split(",") if tok.strip()}
        ids = ids or {"*"}
        add(i, ids)
        if line.strip().startswith(("#", "//")):
            # comment-only allow: extend through the comment block to the
            # first code line
            j = i + 1
            while j <= len(lines) and (
                not lines[j - 1].strip()
                or lines[j - 1].strip().startswith("#")
            ):
                add(j, ids)
                j += 1
            if j <= len(lines):
                add(j, ids)
    return out


def is_suppressed(finding: Finding, sup: dict[int, set[str]]) -> bool:
    for line in (finding.line, finding.line - 1):
        ids = sup.get(line)
        if ids and ("*" in ids or finding.rule in ids):
            return True
    return False


# ----------------------------------------------------------------- driver


def iter_py_files(paths: Iterable[Path]) -> list[Path]:
    files: list[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py":
            files.append(p)
    return files


def analyze_paths(
    paths: Iterable[Path],
    rule_ids: Iterable[str] | None = None,
    root: Path | None = None,
    report_only: set[str] | None = None,
) -> tuple[list[Finding], list[Finding]]:
    """Run the (selected) passes over every ``.py`` under ``paths``.

    Two phases: parse every module and build the shared
    :class:`~tools.analyze.index.ProjectIndex` (symbol table + call graph
    + effect summaries), then run the passes over each module with the
    index in hand — so interprocedural rules see the WHOLE run's modules
    regardless of visit order.

    Returns ``(active, suppressed)`` findings, both sorted. ``root``
    anchors the repo-relative paths in findings (defaults to cwd).
    ``report_only`` (repo-relative posix paths) keeps the index whole-
    program but drops findings outside the named files — the
    ``--changed-only`` fast path.
    """
    # pass modules self-register on import
    import tools.analyze.passes  # noqa: F401
    from tools.analyze.index import ProjectIndex

    root = Path(root) if root is not None else Path.cwd()
    ids = list(rule_ids) if rule_ids else sorted(REGISTRY)
    unknown = [i for i in ids if i not in REGISTRY]
    if unknown:
        raise ValueError(f"unknown rule ids: {', '.join(unknown)}")
    passes = [REGISTRY[i]() for i in ids]

    active: list[Finding] = []
    suppressed: list[Finding] = []

    def bucket(findings: Iterable[Finding], sup: dict[int, set[str]]) -> None:
        for f in findings:
            (suppressed if is_suppressed(f, sup) else active).append(f)

    contexts: list[ModuleContext] = []
    sups: dict[str, dict[int, set[str]]] = {}
    for path in iter_py_files(paths):
        try:
            rel = path.resolve().relative_to(root.resolve()).as_posix()
        except ValueError:
            rel = path.as_posix()
        source = path.read_text(encoding="utf-8", errors="replace")
        try:
            ctx = ModuleContext(path, rel, source)
        except SyntaxError as e:
            active.append(Finding(rel, e.lineno or 1, "parse-error", str(e)))
            continue
        sups[rel] = suppressions(source)
        contexts.append(ctx)

    index = ProjectIndex(contexts)
    for p in passes:
        p.begin(index)
    for ctx in contexts:
        for p in passes:
            bucket(p.visit(ctx), sups[ctx.rel])
    def sup_for(rel: str) -> dict[int, set[str]]:
        # finalize findings can land on files OUTSIDE the analyzed .py
        # set (the native plane): load their pragmas lazily so
        # `// demodel: allow(rule)` works there too
        if rel not in sups:
            try:
                text = (root / rel).read_text(encoding="utf-8",
                                              errors="replace")
            except OSError:
                text = ""
            sups[rel] = suppressions(text)
        return sups[rel]

    for p in passes:
        for f in p.finalize():
            bucket([f], sup_for(f.path))
    if report_only is not None:
        active = [f for f in active if f.path in report_only]
        suppressed = [f for f in suppressed if f.path in report_only]
    key = lambda f: (f.path, f.line, f.rule)  # noqa: E731
    return sorted(active, key=key), sorted(suppressed, key=key)
