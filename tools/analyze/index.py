"""ProjectIndex: repo-wide symbol table + call graph + effect summaries.

The compositional-analysis layer (Infer/RacerD shape): one bottom-up walk
per function collects a small *effect summary* — "returns a device value",
"performs blocking I/O at line N", "acquires lock X", "allocates device
bytes with placement from Y" — and a resolved call graph lets rules
compose those summaries across module boundaries with a call-depth bound,
instead of re-walking the whole tree per query.

Resolution levels (in order):

- bare names → same-module functions (including enclosing-scope nested
  defs);
- ``self.method()`` → the enclosing class's method;
- imported names — ``import a.b as x`` / ``from a.b import c as d`` —
  resolved through the per-module alias table to project definitions;
- ``Class.method`` / ``alias_module.func`` dotted chains;
- constructor-typed locals: ``r = PeerBlobReader(...); r.pread(...)``
  resolves through the local's known class.

- **self-attribute receivers**: a constructor-assigned attribute type
  (``self.budget = ByteBudget(...)`` in any method of the class) is
  recorded in the index, so ``self.budget.acquire(...)`` resolves to
  ``ByteBudget.acquire`` through the call graph instead of the old
  name-heuristic — effect summaries (blocking, locks, budget charges)
  flow through typed attributes;
- **executor-submit edges**: ``ex.submit(f, x)`` (and
  ``Thread(target=f)``) contribute a call-graph edge to ``f`` — the
  submitted callable's effect summary flows through the worker-escaping
  call, so e.g. blocking I/O reachable only via a submit still surfaces
  at the submitting call site.

Receivers typed only at runtime (param-assigned ``self.attr``,
dict-dispatched callables) stay unresolved — passes treat unresolved
calls as effect-free, keeping the analysis under-approximate (no
speculative edges) like the seed's one-level resolution was.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Iterable

from tools.analyze.core import dotted, walk_in_scope

if TYPE_CHECKING:  # pragma: no cover - typing only
    from tools.analyze.core import ModuleContext

LOCKISH_RE = re.compile(r"lock|mutex", re.IGNORECASE)
BUDGETISH_RE = re.compile(r"budget", re.IGNORECASE)

#: jax.* calls that return HOST values (device handles, counts, pytree
#: plumbing) — consuming them on the host is not a sync
HOST_RESULT = {
    "jax.devices", "jax.local_devices", "jax.device_count",
    "jax.local_device_count", "jax.process_count", "jax.process_index",
    "jax.default_backend", "jax.make_mesh", "jax.random.split",
}
HOST_RESULT_PREFIXES = ("jax.tree", "jax.sharding", "jax.dtypes", "jnp.shape")

#: calls that allocate NEW device buffers (the hbm-budget rule's subjects)
DEVICE_ALLOCATORS = {
    "jax.device_put", "jax.make_array_from_single_device_arrays",
}
JNP_ALLOCATORS = {
    "jnp.zeros", "jnp.ones", "jnp.empty", "jnp.full", "jnp.arange",
    "jnp.array", "jnp.asarray", "jnp.linspace", "jnp.eye",
}

_BLOCKING_PREFIXES = ("requests.", "subprocess.", "socket.",
                      "urllib.request.")
_BLOCKING_EXACT = {"time.sleep", "open", "urlopen"}
_BLOCKING_ATTRS = {"recv", "recvfrom", "sendall", "accept", "makefile",
                   "read_bytes", "write_bytes", "read_text", "write_text"}
_HTTP_VERBS = {"get", "post", "put", "patch", "delete", "head", "request"}


def _submitted_callable(call: ast.Call) -> ast.AST | None:
    """The callable REFERENCE a worker-escaping call hands off, or None:
    ``ex.submit(f, x)`` / ``pool.submit(f)`` → ``f``;
    ``Thread(target=f)`` → ``f``; ``asyncio.to_thread(f, x)`` → ``f``."""
    name = dotted(call.func) or ""
    if (name == "submit" or name.endswith(".submit")
            or name == "to_thread" or name.endswith(".to_thread")):
        return call.args[0] if call.args else None
    if name == "Thread" or name.endswith(".Thread"):
        for kw in call.keywords:
            if kw.arg == "target":
                return kw.value
    return None


def device_producer(call: ast.Call) -> bool:
    """Does this call produce a DEVICE value (jnp./jax. minus the
    host-result table)?"""
    name = dotted(call.func)
    if not name:
        return False
    if name in HOST_RESULT or name.startswith(HOST_RESULT_PREFIXES):
        return False
    return name.startswith(("jnp.", "jax."))


def blocking_call(node: ast.Call, ctx: "ModuleContext") -> str | None:
    """Why this call blocks (network/disk/sleep), or None."""
    name = dotted(node.func)
    if name:
        if name in _BLOCKING_EXACT:
            return f"{name}()"
        if name.startswith(_BLOCKING_PREFIXES):
            return f"{name}()"
    if isinstance(node.func, ast.Attribute):
        attr = node.func.attr
        recv = ctx.src(node.func.value)
        if attr in _BLOCKING_ATTRS:
            return f".{attr}() on {recv}"
        if attr in _HTTP_VERBS and "session" in recv.lower():
            return f"HTTP {attr}() on {recv}"
    return None


def lock_id(ctx: "ModuleContext", expr: ast.AST,
            cls: ast.ClassDef | None, fn: ast.AST | None,
            aliases: dict | None = None) -> str | None:
    """Normalized lock identity (``module.Class.attr`` for self members,
    ``module.func.name`` for locals), or None when not lock-shaped.

    ``aliases`` (this module's import table) makes the identity stable
    ACROSS modules: ``with store_mod.store_lock:`` and a ``with
    store_lock:`` inside ``store_mod`` itself normalize to the same
    node, which is what lets the lock graph see cross-module cycles."""
    src = ctx.src(expr)
    if not LOCKISH_RE.search(src):
        return None
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
        if expr.value.id == "self":
            scope = cls.name if cls else "<module>"
            return f"{ctx.module}.{scope}.{expr.attr}"
        if aliases and expr.value.id in aliases:
            # imported-module member: normalize to the owning module
            return f"{aliases[expr.value.id]}.{expr.attr}"
    if isinstance(expr, ast.Name):
        if aliases and expr.id in aliases:
            # from store_mod import store_lock → store_mod.store_lock
            return aliases[expr.id]
        if fn is not None and any(
            isinstance(n, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == expr.id
                for t in n.targets
            )
            for n in ast.walk(fn)
        ):
            name = getattr(fn, "name", "<lambda>")
            return f"{ctx.module}.{name}.{expr.id}"
        return f"{ctx.module}.{expr.id}"
    return f"{ctx.module}.{src}"


@dataclass
class AllocSite:
    """One device-allocating call and where its placement comes from."""

    node: ast.Call
    line: int
    call_name: str                 # jax.device_put / jnp.zeros / ...
    #: "plan" (plan/NamedSharding-derived), ("param", name), or "unknown";
    #: None for allocators with NO placement argument at all
    placement: object = None


@dataclass
class FunctionInfo:
    qname: str
    module: str
    rel: str
    name: str
    node: ast.AST
    cls: str | None = None               # enclosing class qname, if method
    params: list = field(default_factory=list)
    #: resolved call sites: [(qname | None, raw dotted | None, node)]
    calls: list = field(default_factory=list)
    #: does a `return` directly yield a jnp./jax. produced value?
    returns_device_direct: bool = False
    #: callee qnames whose result this function returns (propagation edges)
    returns_calls: set = field(default_factory=set)
    #: first direct blocking call: (line, why) | None
    blocking_direct: tuple | None = None
    #: lock ids acquired anywhere in the body (with-statements)
    acquires: set = field(default_factory=set)
    #: device allocations performed directly in the body
    allocs: list = field(default_factory=list)
    #: calls `.acquire(...)` on a *budget*-named receiver (byte accounting)
    budget_acquire: bool = False
    #: spawns a thread / asyncio task directly
    spawns: bool = False
    #: names the function's body passes to an executor/Thread (escaping
    #: callables — used by hbm-budget's concurrent-buffer clause)
    escapes_to_worker: set = field(default_factory=set)
    #: resolved worker-escaping call edges: [(qname, raw, submit node)]
    #: for ``ex.submit(f, ...)`` / ``Thread(target=f)`` /
    #: ``asyncio.to_thread(f, ...)``. Kept SEPARATE from ``calls``:
    #: work-shaped effects (blocking I/O) compose through them, but lock
    #: ACQUISITION does not — a lock taken on the worker thread is
    #: concurrent with the submitter, not nested inside its critical
    #: section, so feeding it into the lock-order graph would fabricate
    #: cycles.
    submit_calls: list = field(default_factory=list)
    #: acquires-obligation facts: every paired-resource acquire in the
    #: body with its local settle/escape/risk classification
    #: (tools.analyze.obligations.ObligationSite)
    obligations: list = field(default_factory=list)
    #: transfers-ownership facts: param name → first ownership event —
    #: ("released", line) / ("kept", how, line) / ("forwarded", callee
    #: qname, callee param, line) / ("dropped",) — what lets a caller's
    #: handoff compose through the call graph at bounded depth
    param_fate: dict = field(default_factory=dict)
    #: releases-obligation facts: receiver dotted texts this body calls
    #: a release-shaped method on (``self.budget`` when the body has
    #: ``self.budget.release(...)``) — the receiver-carried discipline
    released_receivers: set = field(default_factory=set)


class ProjectIndex:
    """Symbol table + call graph over a set of parsed modules."""

    #: summary composition bound — RacerD-style: deep enough to cross
    #: ops/ → sink/ → delivery chains, shallow enough to stay linear
    MAX_DEPTH = 4

    def __init__(self, contexts: Iterable["ModuleContext"]):
        self.contexts = list(contexts)
        self.by_module: dict[str, "ModuleContext"] = {
            c.module: c for c in self.contexts}
        #: function qname → FunctionInfo
        self.functions: dict[str, FunctionInfo] = {}
        #: id(FunctionDef node) → FunctionInfo (pass-side reverse lookup)
        self.by_node: dict[int, FunctionInfo] = {}
        #: class qname → {method name → function qname}
        self.classes: dict[str, dict[str, str]] = {}
        #: class qname → resolved base-class names (project classes keep
        #: their qname; stdlib bases resolve through the alias table to
        #: e.g. ``http.server.BaseHTTPRequestHandler``) — what lets the
        #: guarded-field pass recognize HTTP-handler-pool entry points
        self.class_bases: dict[str, list[str]] = {}
        #: class qname → {attr name → class qname} for constructor-assigned
        #: attributes (``self.x = KnownClass(...)``) — what lets
        #: ``self.x.m()`` resolve through the call graph
        self.self_attr_types: dict[str, dict[str, str]] = {}
        #: module → {local alias → fully qualified target}
        self.aliases: dict[str, dict[str, str]] = {}
        #: rel path → {id(call node) → resolved qname} (for passes)
        self.resolution: dict[str, dict[int, str]] = {}
        #: rel path → {id(call node) → enclosing FunctionInfo}
        self._owner: dict[str, dict[int, FunctionInfo]] = {}
        self._memo_device: dict = {}
        self._memo_block: dict = {}
        self._memo_locks: dict = {}
        for ctx in self.contexts:
            self._collect_defs(ctx)
        for ctx in self.contexts:
            # needs the full class table, must precede body resolution
            self._collect_self_attr_types(ctx)
        for ctx in self.contexts:
            self._collect_bodies(ctx)

    # ------------------------------------------------------------ build
    def _collect_defs(self, ctx: "ModuleContext") -> None:
        self.aliases[ctx.module] = aliases = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:  # relative import → anchor on this package
                    pkg = ctx.module.split(".")
                    pkg = pkg[: len(pkg) - node.level]
                    base = ".".join(pkg + ([node.module]
                                           if node.module else []))
                for a in node.names:
                    if a.name == "*":
                        continue
                    aliases[a.asname or a.name] = f"{base}.{a.name}"
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qname, cls = self._qname_of(ctx, node)
                info = FunctionInfo(
                    qname=qname, module=ctx.module, rel=ctx.rel,
                    name=node.name, node=node, cls=cls,
                    params=[a.arg for a in (
                        node.args.posonlyargs + node.args.args
                        + node.args.kwonlyargs)],
                )
                self.functions[qname] = info
                self.by_node[id(node)] = info
                if cls is not None:
                    self.classes.setdefault(cls, {})[node.name] = qname
            elif isinstance(node, ast.ClassDef):
                qname, _ = self._qname_of(ctx, node)
                self.classes.setdefault(qname, {})
                self.class_bases[qname] = [
                    b for b in (self._resolve_name(ctx, dotted(base) or "")
                                for base in node.bases)
                    if b is not None]

    @staticmethod
    def _qname_of(ctx: "ModuleContext", node: ast.AST):
        """``module.Outer.name`` plus the nearest enclosing class qname."""
        chain = []
        cls: str | None = None
        cur = getattr(node, "_dm_parent", None)
        nearest_cls_depth = None
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                chain.append(cur.name)
                if isinstance(cur, ast.ClassDef) and nearest_cls_depth is None:
                    nearest_cls_depth = len(chain)
            cur = getattr(cur, "_dm_parent", None)
        chain.reverse()
        if nearest_cls_depth is not None:
            # chain was appended innermost-first, so after reverse the
            # nearest class sits at -nearest_cls_depth
            cls_chain = chain[: len(chain) - nearest_cls_depth + 1]
            cls = f"{ctx.module}." + ".".join(cls_chain)
        qual = ".".join(chain + [node.name]) if chain else node.name
        return f"{ctx.module}.{qual}", cls

    def _collect_self_attr_types(self, ctx: "ModuleContext") -> None:
        """Record constructor-assigned attribute types per class:
        ``self.x = KnownClass(...)`` anywhere in the class's methods makes
        ``self.x`` carry that type for receiver resolution. Only literal
        constructor calls count (param-assigned attrs stay untyped — no
        speculative edges)."""
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            cq, _ = self._qname_of(ctx, node)
            table = self.self_attr_types.setdefault(cq, {})
            from tools.analyze.core import enclosing_class

            for sub in ast.walk(node):
                if not (isinstance(sub, ast.Assign)
                        and len(sub.targets) == 1
                        and isinstance(sub.targets[0], ast.Attribute)
                        and isinstance(sub.targets[0].value, ast.Name)
                        and sub.targets[0].value.id == "self"
                        and isinstance(sub.value, ast.Call)):
                    continue
                if enclosing_class(sub) is not node:
                    continue  # a nested class's attrs are not ours
                q = self._resolve_name(ctx, dotted(sub.value.func) or "")
                if q in self.classes:
                    table[sub.targets[0].attr] = q

    def _collect_bodies(self, ctx: "ModuleContext") -> None:
        res = self.resolution.setdefault(ctx.rel, {})
        own = self._owner.setdefault(ctx.rel, {})
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            info = self.functions[self._qname_of(ctx, node)[0]]
            local_types = self._constructor_types(ctx, node)
            dev_names: set[str] = set()
            call_assigned: dict[str, str] = {}  # name → callee qname
            for sub in walk_in_scope(node):
                if isinstance(sub, ast.Call):
                    q = self._resolve(ctx, node, sub, local_types)
                    info.calls.append((q, dotted(sub.func), sub))
                    if q is not None:
                        res[id(sub)] = q
                    own[id(sub)] = info
                    self._note_effects(ctx, node, info, sub, q)
                    tgt = _submitted_callable(sub)
                    if tgt is not None:
                        q2 = self._resolve_callable_ref(ctx, node, tgt,
                                                        local_types)
                        if q2 is not None:
                            info.submit_calls.append(
                                (q2, dotted(tgt), sub))
                elif isinstance(sub, (ast.With, ast.AsyncWith)):
                    for item in sub.items:
                        lid = lock_id(ctx, item.context_expr,
                                      self._cls_node(ctx, info), node,
                                      self.aliases.get(ctx.module))
                        if lid is not None:
                            info.acquires.add(lid)
                elif isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                        and isinstance(sub.targets[0], ast.Name) \
                        and isinstance(sub.value, ast.Call):
                    tgt = sub.targets[0].id
                    if device_producer(sub.value):
                        dev_names.add(tgt)
                    q = self._resolve(ctx, node, sub.value, local_types)
                    if q is not None:
                        call_assigned[tgt] = q
            for sub in walk_in_scope(node):
                if not isinstance(sub, ast.Return) or sub.value is None:
                    continue
                val = sub.value
                if isinstance(val, ast.Call):
                    if device_producer(val):
                        info.returns_device_direct = True
                    else:
                        q = self._resolve(ctx, node, val, local_types)
                        if q is not None:
                            info.returns_calls.add(q)
                elif isinstance(val, ast.Name):
                    if val.id in dev_names:
                        info.returns_device_direct = True
                    elif val.id in call_assigned:
                        info.returns_calls.add(call_assigned[val.id])
            # obligation facts ride the same pass: one extra scoped walk
            # per body, resolution map already populated above
            from tools.analyze import obligations

            obligations.collect(ctx, node, info, self)

    def _cls_node(self, ctx: "ModuleContext",
                  info: FunctionInfo) -> ast.ClassDef | None:
        from tools.analyze.core import enclosing_class

        return enclosing_class(info.node)

    def _constructor_types(self, ctx: "ModuleContext",
                           fn: ast.AST) -> dict[str, str]:
        """Locals typed by a constructor call to a known project class."""
        out: dict[str, str] = {}
        for sub in walk_in_scope(fn):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name) \
                    and isinstance(sub.value, ast.Call):
                q = self._resolve_name(ctx, dotted(sub.value.func) or "")
                if q in self.classes:
                    out[sub.targets[0].id] = q
        return out

    def _resolve_name(self, ctx: "ModuleContext", name: str) -> str | None:
        """Resolve a dotted name through this module's alias table."""
        if not name:
            return None
        parts = name.split(".")
        aliases = self.aliases.get(ctx.module, {})
        if parts[0] in aliases:
            return ".".join([aliases[parts[0]]] + parts[1:])
        return f"{ctx.module}.{name}"

    def resolve_class(self, ctx: "ModuleContext", name: str) -> str | None:
        """Resolve ``name`` to a project class qname, or None."""
        q = self._resolve_name(ctx, name)
        return q if q in self.classes else None

    def _resolve(self, ctx: "ModuleContext", fn: ast.AST, call: ast.Call,
                 local_types: dict[str, str]) -> str | None:
        """Resolve a call to a project function qname, or None."""
        name = dotted(call.func)
        if name is None:
            return None
        parts = name.split(".")
        # self.method()
        if parts[0] == "self" and len(parts) == 2:
            from tools.analyze.core import enclosing_class

            cls = enclosing_class(call)
            if cls is not None:
                cq, _ = self._qname_of(ctx, cls)
                return self.classes.get(cq, {}).get(parts[1])
            return None
        # self.attr.method() through the constructor-assigned attr type
        if parts[0] == "self" and len(parts) == 3:
            from tools.analyze.core import enclosing_class

            cls = enclosing_class(call)
            if cls is not None:
                cq, _ = self._qname_of(ctx, cls)
                attr_q = self.self_attr_types.get(cq, {}).get(parts[1])
                if attr_q is not None:
                    return self.classes.get(attr_q, {}).get(parts[2])
            return None
        # constructor-typed local receiver: r.pread()
        if len(parts) == 2 and parts[0] in local_types:
            return self.classes.get(local_types[parts[0]], {}).get(parts[1])
        resolved = self._resolve_name(ctx, name)
        if resolved in self.functions:
            return resolved
        # Class(...) constructor → its __init__ when indexed
        if resolved in self.classes:
            return self.classes[resolved].get("__init__")
        # bare name defined in an enclosing scope (nested defs)
        if len(parts) == 1:
            scope_q, _ = self._qname_of(ctx, fn)
            prefix = scope_q
            while "." in prefix:
                prefix = prefix.rsplit(".", 1)[0]
                cand = f"{prefix}.{name}"
                if cand in self.functions:
                    return cand
        return None

    def _resolve_callable_ref(self, ctx: "ModuleContext", fn: ast.AST,
                              expr: ast.AST,
                              local_types: dict[str, str]) -> str | None:
        """Resolve a callable REFERENCE (not a call) — the ``f`` in
        ``ex.submit(f, x)``. Same resolution levels as :meth:`_resolve`
        minus the constructor arm (a class reference handed to a worker
        is a construction, out of scope)."""
        from tools.analyze.core import enclosing_class

        name = dotted(expr)
        if not name:
            return None
        parts = name.split(".")
        if parts[0] == "self" and len(parts) in (2, 3):
            cls = enclosing_class(expr)
            if cls is None:
                return None
            cq, _ = self._qname_of(ctx, cls)
            if len(parts) == 2:
                return self.classes.get(cq, {}).get(parts[1])
            attr_q = self.self_attr_types.get(cq, {}).get(parts[1])
            if attr_q is not None:
                return self.classes.get(attr_q, {}).get(parts[2])
            return None
        if len(parts) == 2 and parts[0] in local_types:
            return self.classes.get(local_types[parts[0]], {}).get(parts[1])
        resolved = self._resolve_name(ctx, name)
        if resolved in self.functions:
            return resolved
        if len(parts) == 1:
            scope_q, _ = self._qname_of(ctx, fn)
            prefix = scope_q
            while "." in prefix:
                prefix = prefix.rsplit(".", 1)[0]
                cand = f"{prefix}.{name}"
                if cand in self.functions:
                    return cand
        return None

    def _note_effects(self, ctx: "ModuleContext", fn: ast.AST,
                      info: FunctionInfo, call: ast.Call,
                      resolved: str | None = None) -> None:
        name = dotted(call.func) or ""
        if info.blocking_direct is None:
            why = blocking_call(call, ctx)
            if why is not None:
                info.blocking_direct = (call.lineno, why)
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr == "acquire" \
                and (BUDGETISH_RE.search(ctx.src(call.func.value))
                     or (resolved is not None
                         and BUDGETISH_RE.search(resolved))):
            # budget-charging detection: the receiver NAME matches (the
            # seed heuristic), or the call RESOLVES — via constructor-typed
            # locals / self-attrs — to a method of a budget-named class
            # (``self.limiter = ByteBudget(...); self.limiter.acquire``)
            info.budget_acquire = True
        if name == "Thread" or name.endswith(".Thread") \
                or name.endswith(("create_task", "ensure_future")):
            info.spawns = True
        if name in DEVICE_ALLOCATORS or name in JNP_ALLOCATORS:
            info.allocs.append(AllocSite(
                node=call, line=call.lineno, call_name=name))
        # callables escaping to worker threads/executors (bare names and
        # same-class bound methods: ex.submit(self._fetch, job))
        if name.endswith(".submit") and call.args:
            tgt = call.args[0]
            if isinstance(tgt, ast.Name):
                info.escapes_to_worker.add(tgt.id)
            elif isinstance(tgt, ast.Attribute) \
                    and isinstance(tgt.value, ast.Name) \
                    and tgt.value.id == "self":
                info.escapes_to_worker.add(tgt.attr)
        if name == "Thread" or name.endswith(".Thread"):
            for kw in call.keywords:
                if kw.arg == "target" and isinstance(kw.value, ast.Name):
                    info.escapes_to_worker.add(kw.value.id)

    # -------------------------------------------------- composed queries
    def callers_of(self, qname: str) -> list:
        """[(caller FunctionInfo, call node)] for every resolved call."""
        out = []
        for info in self.functions.values():
            for q, _raw, node in info.calls:
                if q == qname:
                    out.append((info, node))
        return out

    def returns_device(self, qname: str, depth: int | None = None) -> bool:
        """Does ``qname`` (transitively, bounded) return a device value?"""
        depth = self.MAX_DEPTH if depth is None else depth
        key = (qname, depth)
        if key in self._memo_device:
            return self._memo_device[key]
        self._memo_device[key] = False  # cycle guard: assume host
        info = self.functions.get(qname)
        out = False
        if info is not None:
            if info.returns_device_direct:
                out = True
            elif depth > 0:
                out = any(self.returns_device(q, depth - 1)
                          for q in info.returns_calls)
        self._memo_device[key] = out
        return out

    def blocking(self, qname: str, depth: int | None = None) -> tuple | None:
        """``(line, why, via)`` when calling ``qname`` can block on
        network/disk/sleep (bounded transitive), else None. ``via`` is the
        qname whose body holds the direct blocking call."""
        depth = self.MAX_DEPTH if depth is None else depth
        key = (qname, depth)
        if key in self._memo_block:
            return self._memo_block[key]
        self._memo_block[key] = None  # cycle guard
        info = self.functions.get(qname)
        out = None
        if info is not None:
            if info.blocking_direct is not None:
                out = (*info.blocking_direct, qname)
            elif depth > 0:
                # submit_calls compose here too: blocking work a function
                # hands to an executor still happens on its behalf (and a
                # `.result()` wait makes it block for real) — while lock
                # ACQUISITION deliberately does not flow through these
                # edges (see FunctionInfo.submit_calls)
                for q, _raw, node in info.calls + info.submit_calls:
                    if q is None or q == qname:
                        continue
                    sub = self.blocking(q, depth - 1)
                    if sub is not None:
                        out = sub
                        break
        self._memo_block[key] = out
        return out

    def acquired_locks(self, qname: str, depth: int | None = None) -> set:
        """Lock ids ``qname`` may acquire, bounded-transitively."""
        depth = self.MAX_DEPTH if depth is None else depth
        key = (qname, depth)
        if key in self._memo_locks:
            return self._memo_locks[key]
        self._memo_locks[key] = set()  # cycle guard
        info = self.functions.get(qname)
        out: set = set()
        if info is not None:
            out |= info.acquires
            if depth > 0:
                for q, _raw, _node in info.calls:
                    if q is not None and q != qname:
                        out |= self.acquired_locks(q, depth - 1)
        self._memo_locks[key] = out
        return out

    def owner_of(self, ctx_rel: str, call: ast.Call) -> FunctionInfo | None:
        return self._owner.get(ctx_rel, {}).get(id(call))

    def resolve_in(self, ctx_rel: str, call: ast.Call) -> str | None:
        return self.resolution.get(ctx_rel, {}).get(id(call))
