"""Clang-free C++ concurrency index over a native tree.

The native serve plane's concurrency discipline — lock-set guarded
fields, the 27-rank lock order, the single-owner reactor — is checked
dynamically (DM_LOCK_ORDER_CHECK under the TSan selftests), which sees
exactly the interleavings the selftests drive. This module grows the
:mod:`tools.analyze.native_index` regex-level scanner into the shared
index three static rules need to make those invariants whole-program:

- **classes + members** — every ``class``/``struct`` body parsed into
  member declarations classified as ranked mutex (``Mutex m_{kRank…}``
  / ``DM_RANKED``), plain mutex (``std::mutex`` or a rank-capable
  wrapper with no rank), atomic, condition variable, thread, or data.
- **functions with lambda splitting** — thread-entry lambdas
  (``[this]{ worker_loop(); }``) are carved out of their enclosing
  function into synthetic functions so accesses inside them attribute
  to the SPAWNED thread, not the spawning one. Statements carry block
  paths, so a lexical ``std::lock_guard`` region is exactly the suffix
  of its block.
- **lock regions** — ``lock_guard``/``unique_lock``/``scoped_lock``
  declarations open a region for the rest of their block; lock names
  canonicalize to ``Class::member`` (``fill->mu`` and ``sf_fill->mu``
  are one logical guard: the owning object's field, RacerD-style).
- **call graph + roots** — bare and typed-receiver calls resolved with
  no speculation (unresolved edges stay silent); thread roots from
  ``std::thread``/thread-vector spawn sites and the ``extern "C"`` API
  surface, with multi-instance marking (worker pools, API callers).
  Lifecycle functions (those constructing or joining threads) CUT the
  root closure: code reachable only through start()/stop() runs
  single-threaded before spawn / after join.
- **caller-held composition** — ``must_hold(fn)`` is the intersection
  of locks held at every call site, composed through the call graph at
  bounded depth: a helper with no guard of its own is still protected
  when every caller holds the lock (the Python plane's exact
  contract).

Everything the regex level cannot resolve — receivers of unknown type,
calls with no unique target — contributes NO edge and NO access: the
same no-speculative-edges posture as the rest of the analyzer.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from pathlib import Path

from tools.analyze.native_index import (
    _FN_OPEN_RE,
    _KEYWORDS,
    _balanced,
    _line_of,
    _match_name,
    strip_code,
)

#: shared anchoring pragma for the three concurrency rules — fixtures
#: point a .py file at a miniature native tree with this
PRAGMA_RE = re.compile(r"#\s*demodel:\s*concurrency-native=(\S+)")

#: caller-held / transitive-acquisition composition bound (matches the
#: Python guarded-field plane)
MAX_DEPTH = 4

RANK_RE = re.compile(r"constexpr\s+int\s+(kRank\w+)\s*=\s*(\d+)\s*;")

#: files never indexed for concurrency: the ranked-mutex shim IS the
#: wrapper implementation (its internal std::mutex is the mechanism,
#: not a missing rank), and the selftest harness is single-purpose
#: TSan-driven code with its own thread model
EXCLUDED_FILES = ("lock_order.h", "selftest.cc")

_CLASS_RE = re.compile(
    r"\b(class|struct)\s+([A-Za-z_]\w*)\s*(?:final\s*)?(?::[^{;]*)?\{")

_GUARD_RE = re.compile(
    r"\b(?:std::)?(lock_guard|unique_lock|scoped_lock|shared_lock)\s*"
    r"(?:<[^;>]*>)?\s+(\w+)\s*([\(\{])")

_LAMBDA_RE = re.compile(
    r"(?<![\w\)\]])\[[^\[\]]*\]\s*(?:\(([^()]*)\))?\s*(?:mutable\b\s*)?"
    r"(?:noexcept\b\s*)?(?:->\s*[\w:<>&*\s]+?)?\{")

_INIT_LIST_RE = re.compile(
    r"\)\s*:\s*(?:[A-Za-z_][\w:]*\s*"
    r"(?:\((?:[^()]|\([^()]*\))*\)|\{[^{}]*\})\s*,\s*)*"
    r"[A-Za-z_][\w:]*\s*(?:\((?:[^()]|\([^()]*\))*\)|\{[^{}]*\})\s*$")

_ATOMIC_OPS = (
    "load", "store", "exchange", "fetch_add", "fetch_sub", "fetch_and",
    "fetch_or", "fetch_xor", "compare_exchange_weak",
    "compare_exchange_strong")

_ATOMIC_OP_RE = re.compile(
    r"(?:(\w+)\s*(?:->|\.)\s*)?(\w+)\s*\.\s*(%s)\s*\(" %
    "|".join(_ATOMIC_OPS))

_MUTATOR_RE = re.compile(
    r"\.\s*(?:push_back|push_front|pop_back|pop_front|insert|erase|"
    r"clear|resize|assign|reserve|append|reset|swap|"
    r"emplace(?:_back|_front)?)\s*\(")

_CALL_KEYWORDS = _KEYWORDS | {
    "new", "delete", "case", "else", "do", "throw", "operator",
    "static_cast", "reinterpret_cast", "const_cast", "dynamic_cast",
    "noexcept", "alignas", "typeid", "co_return", "co_await",
}


def _strip_pp(text: str) -> str:
    """Blank preprocessor lines (and their backslash continuations) —
    offsets preserved."""
    out = []
    cont = False
    for line in text.split("\n"):
        if cont or line.lstrip().startswith("#"):
            cont = line.rstrip().endswith("\\")
            out.append(" " * len(line))
        else:
            cont = False
            out.append(line)
    return "\n".join(out)


# ----------------------------------------------------------------- model


@dataclass
class CMember:
    cls: str
    name: str
    kind: str          # mutex | atomic | cv | thread | data
    rank: str | None   # kRank… constant for ranked mutexes
    rel: str
    line: int
    type_text: str = ""


@dataclass
class CStmt:
    line: int
    text: str
    blocks: tuple      # enclosing block ids (path from function root)
    conds: list        # texts of enclosing/inline conditions
    span: tuple        # (start, end) offsets in the file text


@dataclass
class CFunction:
    qname: str
    cls: str | None
    short: str
    rel: str
    line: int
    start: int
    end: int
    header: str
    statements: list = field(default_factory=list)
    block_heads: dict = field(default_factory=dict)  # block id → head text
    is_lambda: bool = False
    parent: str | None = None
    api: bool = False
    # filled by the analysis phase
    local_types: dict = field(default_factory=dict)  # var → class name
    locals: set = field(default_factory=set)
    #: locals this function OWNS (value declarations and `= new Cls`
    #: results): writes through them are pre-escape, not shared
    owned: set = field(default_factory=set)
    guards: list = field(default_factory=list)   # (stmt idx, lock, line)
    held: list = field(default_factory=list)     # per-stmt frozenset
    calls: list = field(default_factory=list)    # (callee, line, held)
    accesses: list = field(default_factory=list)
    lifecycle: bool = False


@dataclass
class Access:
    cls: str
    member: str
    write: bool
    rel: str
    line: int
    locks: frozenset   # lexical lock set at the site
    fn: str
    atomic: bool = False
    op: str = ""


@dataclass
class Root:
    key: str          # entry function qname
    label: str        # human name (worker_loop, reactor_loop, api, …)
    multi: bool       # more than one concurrent instance can exist


class ConcurrencyIndex:
    """Everything the three concurrency rules read for one native dir."""

    def __init__(self) -> None:
        self.classes: dict[str, dict[str, CMember]] = {}
        self.functions: dict[str, CFunction] = {}
        self.by_short: dict[str, list[str]] = {}
        self.ranks: dict[str, tuple[int, str, int]] = {}
        self.rank_uses: dict[str, int] = {}
        self.member_types: dict[tuple[str, str], str] = {}
        self.roots: dict[str, Root] = {}
        self.fn_roots: dict[str, set[str]] = {}
        self.reactor_roots: set[str] = set()
        self.handoff_fns: set[str] = set()
        self.inbox_members: set[tuple[str, str]] = set()
        self.callers: dict[str, list] = {}
        self._mh_memo: dict[str, frozenset] = {}
        self._acq_memo: dict[str, dict] = {}
        self._lambda_seq = 0

    # ------------------------------------------------- composed lock sets
    def must_hold(self, q: str, depth: int = 0,
                  seen: set | None = None) -> frozenset:
        """Locks held at EVERY call site of ``q``, composed through the
        call graph to MAX_DEPTH — the caller-held half of a site's
        effective lock set."""
        if q in self._mh_memo:
            return self._mh_memo[q]
        if seen is None:
            seen = set()
        if depth > MAX_DEPTH or q in seen:
            return frozenset()
        seen.add(q)
        callers = self.callers.get(q, [])
        if not callers:
            res: frozenset = frozenset()
        else:
            sets = [held | self.must_hold(c, depth + 1, seen)
                    for c, held in callers]
            res = frozenset.intersection(*sets)
        if depth == 0:
            self._mh_memo[q] = res
        return res

    def eff_locks(self, acc: Access) -> frozenset:
        return acc.locks | self.must_hold(acc.fn)

    def acquired_within(self, q: str, depth: int = 0,
                        seen: set | None = None) -> dict:
        """Ranked locks acquired by ``q`` or its callees (bounded
        depth) → call-chain path tuple, for lock-order edge blame."""
        if q in self._acq_memo:
            return self._acq_memo[q]
        if seen is None:
            seen = set()
        if depth > MAX_DEPTH or q in seen:
            return {}
        seen.add(q)
        fn = self.functions.get(q)
        if fn is None:
            return {}
        out: dict[str, tuple] = {}
        for _idx, lock, _line in fn.guards:
            if self.rank_of(lock) is not None:
                out.setdefault(lock, ())
        for callee, _line, _held in fn.calls:
            for lock, path in self.acquired_within(
                    callee, depth + 1, seen).items():
                out.setdefault(lock, (callee,) + path)
        if depth == 0:
            self._acq_memo[q] = out
        return out

    def rank_of(self, lock: str) -> int | None:
        name = lock.rsplit("::", 1)[-1]
        cls = lock.rsplit("::", 1)[0] if "::" in lock else None
        if cls and cls in self.classes:
            mem = self.classes[cls].get(name)
            if mem is not None and mem.rank in self.ranks:
                return self.ranks[mem.rank][0]
        return None

    def roots_of(self, q: str) -> set[str]:
        return self.fn_roots.get(q, set())


# ------------------------------------------------------------ extraction


def _parse_param_locals(idx: ConcurrencyIndex, fn: CFunction) -> None:
    header = fn.header
    op = header.find("(")
    if op < 0:
        return
    close = op
    depth = 0
    for i in range(op, len(header)):
        if header[i] == "(":
            depth += 1
        elif header[i] == ")":
            depth -= 1
            if depth == 0:
                close = i
                break
    params = header[op + 1:close]
    for part in _split_commas(params):
        part = part.split("=", 1)[0].strip()
        m = re.match(
            r"(?:const\s+)?([A-Za-z_][\w:]*(?:<[^<>]*>)?)"
            r"[\s*&]+([A-Za-z_]\w*)\s*(?:\[[^\]]*\])?$", part)
        if not m:
            continue
        fn.locals.add(m.group(2))
        base = m.group(1).rsplit("::", 1)[-1].split("<", 1)[0]
        if base in idx.classes:
            fn.local_types[m.group(2)] = base


def _split_commas(text: str) -> list[str]:
    out, depth, start = [], 0, 0
    for i, c in enumerate(text):
        if c in "<([{":
            depth += 1
        elif c in ">)]}":
            depth -= 1
        elif c == "," and depth <= 0:
            out.append(text[start:i])
            start = i + 1
    out.append(text[start:])
    return [p for p in (s.strip() for s in out) if p]


_LOCAL_DECL_RE = re.compile(
    r"^(?:const\s+)?(?!return\b|delete\b|throw\b|new\b|case\b|goto\b)"
    r"([A-Za-z_][\w:]*(?:<[^<>]*>)?)\s*([*&]*)\s*([A-Za-z_]\w*)\s*"
    r"(?:=(?!=)|;|\{|$|\[)")
_CAST_DECL_RE = re.compile(
    r"\b(?:auto\s*\*?\s*)?(\w+)\s*=\s*static_cast<\s*([A-Z]\w*)\s*\*")
_NEW_DECL_RE = re.compile(r"\b(\w+)\s*=\s*new\s+([A-Z]\w*)\b")
_PTR_DECL_RE = re.compile(r"\b([A-Z]\w*)\s*\*\s*(\w+)\s*[=;,):]")
_REF_DECL_RE = re.compile(r"\b([A-Z]\w*)\s*&\s*(\w+)\s*[=;,):]")


def _collect_locals(idx: ConcurrencyIndex, fn: CFunction) -> None:
    _parse_param_locals(idx, fn)
    for st in fn.statements:
        t = st.text
        m = _LOCAL_DECL_RE.match(t)
        if m and m.group(1) not in ("struct", "class", "enum"):
            name = m.group(3)
            fn.locals.add(name)
            base = m.group(1).rsplit("::", 1)[-1].split("<", 1)[0]
            if base in idx.classes:
                fn.local_types.setdefault(name, base)
                if not m.group(2):
                    fn.owned.add(name)  # value local: a private copy
        for rx in (_PTR_DECL_RE, _REF_DECL_RE):
            for dm in rx.finditer(t):
                if dm.group(1) in idx.classes:
                    fn.locals.add(dm.group(2))
                    fn.local_types.setdefault(dm.group(2), dm.group(1))
        for dm in _CAST_DECL_RE.finditer(t):
            if dm.group(2) in idx.classes:
                fn.local_types[dm.group(1)] = dm.group(2)
        for dm in _NEW_DECL_RE.finditer(t):
            if dm.group(2) in idx.classes:
                fn.local_types.setdefault(dm.group(1), dm.group(2))
                fn.owned.add(dm.group(1))  # fresh object, pre-escape


def _receiver_type(idx: ConcurrencyIndex, fn: CFunction,
                   recv: str) -> str | None:
    if recv == "this":
        return fn.cls
    t = fn.local_types.get(recv)
    if t:
        return t
    if fn.cls:
        t = idx.member_types.get((fn.cls, recv))
        if t:
            return t
    return None


def _canon_lock(idx: ConcurrencyIndex, fn: CFunction, arg: str) -> str:
    a = re.sub(r"\s+", "", arg)
    a = a.lstrip("&*")
    if a.startswith("this->"):
        a = a[len("this->"):]
    m = re.match(r"^(\w+)(?:->|\.)(\w+)$", a)
    if m:
        recv, name = m.group(1), m.group(2)
        tcls = _receiver_type(idx, fn, recv)
        if tcls and name in idx.classes.get(tcls, {}):
            return f"{tcls}::{name}"
        owners = [c for c, mems in sorted(idx.classes.items())
                  if name in mems and mems[name].kind == "mutex"]
        if len(owners) == 1:
            return f"{owners[0]}::{name}"
        return name
    if fn.cls and a in idx.classes.get(fn.cls, {}):
        return f"{fn.cls}::{a}"
    owners = [c for c, mems in sorted(idx.classes.items())
              if a in mems and mems[a].kind == "mutex"]
    if len(owners) == 1:
        return f"{owners[0]}::{a}"
    return a


def _guard_args(text: str, open_pos: int) -> list[str]:
    """Top-level args of the guard constructor whose ( or { is at
    open_pos."""
    close = open_pos
    depth = 0
    pairs = {"(": ")", "{": "}"}
    opener = text[open_pos]
    closer = pairs[opener]
    for i in range(open_pos, len(text)):
        if text[i] == opener:
            depth += 1
        elif text[i] == closer:
            depth -= 1
            if depth == 0:
                close = i
                break
    return _split_commas(text[open_pos + 1:close])


def _compute_guards(idx: ConcurrencyIndex, fn: CFunction) -> None:
    for i, st in enumerate(fn.statements):
        for gm in _GUARD_RE.finditer(st.text):
            args = _guard_args(st.text, gm.end() - 1)
            if any("defer_lock" in a or "try_to_lock" in a for a in args):
                continue
            locks = [a for a in args
                     if "adopt_lock" not in a and not a.isdigit()]
            for arg in locks:
                fn.guards.append(
                    (i, _canon_lock(idx, fn, arg), st.line))
    held = []
    for j, st in enumerate(fn.statements):
        cur = set()
        for i, lock, _line in fn.guards:
            if j <= i:
                continue
            gb = fn.statements[i].blocks
            if st.blocks[:len(gb)] == gb:
                cur.add(lock)
        held.append(frozenset(cur))
    fn.held = held


# ------------------------------------------------------------- accesses

_QUAL_ACCESS_RE = re.compile(
    r"(\w+)(\[[^\]]*\])?\s*(?:->|\.)\s*([A-Za-z_]\w*)\b")
_BARE_ACCESS_RE = re.compile(r"(?<![\w.>])([A-Za-z_]\w*)\b")
_ASSIGN_RE = re.compile(r"^(?:\+|-|\*|/|%|&&?|\|\|?|\^|<<|>>)?=(?!=)")


def _skip_subscripts(text: str, pos: int) -> int:
    while pos < len(text):
        rest = text[pos:]
        ws = len(rest) - len(rest.lstrip())
        if pos + ws < len(text) and text[pos + ws] == "[":
            depth = 0
            i = pos + ws
            while i < len(text):
                if text[i] == "[":
                    depth += 1
                elif text[i] == "]":
                    depth -= 1
                    if depth == 0:
                        break
                i += 1
            pos = i + 1
        else:
            return pos
    return pos


def _is_write_at(text: str, start: int, end: int) -> bool:
    after = text[_skip_subscripts(text, end):].lstrip()
    if after.startswith("++") or after.startswith("--"):
        return True
    if _ASSIGN_RE.match(after):
        return True
    if _MUTATOR_RE.match(after):
        return True
    before = text[:start].rstrip()
    if before.endswith("++") or before.endswith("--"):
        return True
    if re.search(r"\.\s*swap\s*\(\s*&?$", before):
        return True
    if re.search(r"\b(?:memset|memcpy|bzero)\s*\(\s*&?\s*$", before):
        return True
    return False


def _member_lookup(idx: ConcurrencyIndex, fn: CFunction, recv: str | None,
                  name: str) -> CMember | None:
    """Resolve an access with no speculation: typed receiver first,
    then enclosing class (bare, unshadowed), then a globally unique
    member name."""
    if recv is not None:
        tcls = _receiver_type(idx, fn, recv)
        if tcls:
            return idx.classes.get(tcls, {}).get(name)
        owners = [c for c, mems in sorted(idx.classes.items())
                  if name in mems]
        if len(owners) == 1:
            return idx.classes[owners[0]][name]
        return None
    if name in fn.locals:
        return None
    if fn.cls and name in idx.classes.get(fn.cls, {}):
        return idx.classes[fn.cls][name]
    return None


def _compute_accesses(idx: ConcurrencyIndex, fn: CFunction) -> None:
    for j, st in enumerate(fn.statements):
        t = st.text
        seen_spans: list[tuple[int, int]] = []
        # atomic member operations first (they look like method calls)
        for m in _ATOMIC_OP_RE.finditer(t):
            recv, name, op = m.group(1), m.group(2), m.group(3)
            if recv in fn.owned:
                continue  # touch through an owned local: pre-escape
            mem = _member_lookup(idx, fn, recv, name) if recv else \
                _member_lookup(idx, fn, None, name)
            if mem is None or mem.kind != "atomic":
                continue
            seen_spans.append((m.start(), m.end()))
            fn.accesses.append(Access(
                mem.cls, mem.name, op != "load", fn.rel, st.line,
                fn.held[j], fn.qname, atomic=True, op=op))
        covered = list(seen_spans)
        for m in _QUAL_ACCESS_RE.finditer(t):
            if re.match(r"\s*\(", t[m.end():]):
                continue  # method call — the call graph's business
            if any(s <= m.start() < e for s, e in covered):
                continue
            if m.group(1) in fn.owned:
                continue  # write through an owned local: pre-escape
            mem = _member_lookup(idx, fn, m.group(1), m.group(3))
            if mem is None or mem.kind in ("mutex", "cv", "thread"):
                continue
            covered.append((m.start(), m.end()))
            fn.accesses.append(Access(
                mem.cls, mem.name,
                _is_write_at(t, m.start(), m.end()), fn.rel, st.line,
                fn.held[j], fn.qname, atomic=(mem.kind == "atomic")))
        for m in _BARE_ACCESS_RE.finditer(t):
            if any(s <= m.start() < e for s, e in covered):
                continue
            if re.match(r"\s*\(", t[m.end():]):
                continue
            name = m.group(1)
            if name in _CALL_KEYWORDS or name in _KEYWORDS:
                continue
            mem = _member_lookup(idx, fn, None, name)
            if mem is None or mem.kind in ("mutex", "cv", "thread"):
                continue
            fn.accesses.append(Access(
                mem.cls, mem.name,
                _is_write_at(t, m.start(), m.end()), fn.rel, st.line,
                fn.held[j], fn.qname, atomic=(mem.kind == "atomic")))


# ------------------------------------------------------------ call graph

_CALL_RE = re.compile(
    r"(?:(\w+)(?:\[[^\]]*\])?\s*(->|\.)\s*)?([A-Za-z_]\w*)\s*\(")
_NEW_RE = re.compile(r"\bnew\s+([A-Z]\w*)\s*[\(\{]")
_DELETE_RE = re.compile(r"\bdelete\s+(?:\[\]\s*)?(\w+)\b")
_DECL_CTOR_RE = re.compile(r"\b([A-Z]\w*)\s+(\w+)\s*\(")


def _resolve_call(idx: ConcurrencyIndex, fn: CFunction, recv: str | None,
                  name: str) -> str | None:
    if name in _CALL_KEYWORDS:
        return None
    if recv is None or recv == "this":
        lam = f"{fn.qname}::{name}"
        if lam in idx.functions:
            return lam
        if fn.parent:
            plam = f"{fn.parent}::{name}"
            if plam in idx.functions:
                return plam
        if fn.cls and f"{fn.cls}::{name}" in idx.functions:
            return f"{fn.cls}::{name}"
        cands = idx.by_short.get(name, [])
        if len(cands) == 1:
            return cands[0]
        return None
    tcls = _receiver_type(idx, fn, recv)
    if tcls and f"{tcls}::{name}" in idx.functions:
        return f"{tcls}::{name}"
    # an unknown receiver type gets NO fallback: `fd_cache_.begin()`
    # must not resolve to Store::begin just because the short name is
    # unique in the tree
    return None


def _compute_calls(idx: ConcurrencyIndex, fn: CFunction) -> None:
    for j, st in enumerate(fn.statements):
        t = st.text
        for m in _CALL_RE.finditer(t):
            callee = _resolve_call(idx, fn, m.group(1), m.group(3))
            if callee:
                fn.calls.append((callee, st.line, fn.held[j]))
        for m in _NEW_RE.finditer(t):
            ctor = f"{m.group(1)}::{m.group(1)}"
            if ctor in idx.functions:
                fn.calls.append((ctor, st.line, fn.held[j]))
        for m in _DELETE_RE.finditer(t):
            tcls = _receiver_type(idx, fn, m.group(1)) or \
                fn.local_types.get(m.group(1))
            if tcls:
                dtor = f"{tcls}::~{tcls}"
                if dtor in idx.functions:
                    fn.calls.append((dtor, st.line, fn.held[j]))
        for m in _DECL_CTOR_RE.finditer(t):
            if m.group(1) in idx.classes and \
                    f"{m.group(1)}::{m.group(1)}" in idx.functions:
                fn.local_types.setdefault(m.group(2), m.group(1))
                fn.calls.append((f"{m.group(1)}::{m.group(1)}",
                                 st.line, fn.held[j]))


# ----------------------------------------------------------------- roots

_SPAWN_HINT_RE = re.compile(
    r"std::thread\b|\.\s*(?:emplace_back|push_back)\s*\(")
_THREAD_ASSIGN_RE = re.compile(r"\b(\w+_?)\s*=\s*std::thread")
_LOOP_HEAD_RE = re.compile(r"^\s*(?:for|while)\s*\(")


def _is_lifecycle(fn: CFunction) -> bool:
    for st in fn.statements:
        if "std::thread" in st.text or re.search(
                r"\.\s*join\s*\(|\bpthread_join\s*\(", st.text):
            return True
    return False


def _spawn_target(idx: ConcurrencyIndex, fn: CFunction,
                  st: CStmt, lambdas_by_start: dict) -> str | None:
    """The synthetic lambda function spawned by this statement, if
    any — or a named entry from `std::thread(&Cls::fn, …)`."""
    for off, lam_q in lambdas_by_start.items():
        if st.span[0] <= off < st.span[1]:
            lam = idx.functions.get(lam_q)
            if lam is not None and lam.parent == fn.qname:
                return lam_q
    m = re.search(r"std::thread\s*\(\s*&?([A-Za-z_][\w:]*)", st.text)
    if m:
        name = m.group(1)
        if name in idx.functions:
            return name
        short = name.rsplit("::", 1)[-1]
        cands = idx.by_short.get(short, [])
        if len(cands) == 1:
            return cands[0]
    return None


def _root_label(idx: ConcurrencyIndex, fn: CFunction, st: CStmt,
                entry: str) -> str:
    lam = idx.functions.get(entry)
    if lam is not None and lam.is_lambda:
        body_calls = [c for c, _l, _h in lam.calls]
        stmts = [s for s in lam.statements if s.text]
        if len(stmts) == 1 and len(body_calls) == 1:
            return body_calls[0].rsplit("::", 1)[-1]
    m = _THREAD_ASSIGN_RE.search(st.text)
    if m:
        return m.group(1).rstrip("_")
    return entry.rsplit("::", 1)[-1]


def _compute_roots(idx: ConcurrencyIndex, lambdas_by_start: dict) -> None:
    spawn_counts: dict[str, int] = {}
    spawns: list[tuple[CFunction, CStmt, str]] = []
    for q in sorted(idx.functions):
        fn = idx.functions[q]
        for st in fn.statements:
            if not _SPAWN_HINT_RE.search(st.text):
                continue
            target = _spawn_target(idx, fn, st, lambdas_by_start)
            if target is None:
                continue
            is_thread = "std::thread" in st.text
            if not is_thread:
                # …emplace_back(<lambda>) only spawns when the receiver
                # is a thread container
                rm = re.search(
                    r"(\w+)\s*\.\s*(?:emplace_back|push_back)\s*\(",
                    st.text)
                mem = _member_lookup(idx, fn, None, rm.group(1)) \
                    if rm else None
                if mem is None or mem.kind != "thread":
                    continue
            spawn_counts[target] = spawn_counts.get(target, 0) + 1
            spawns.append((fn, st, target))
    for fn, st, target in spawns:
        in_loop = bool(_LOOP_HEAD_RE.match(st.text))
        for bid in st.blocks:
            head = fn.block_heads.get(bid, "")
            if re.search(r"\b(?:for|while)\s*\(", head):
                in_loop = True
        multi = in_loop or spawn_counts[target] > 1
        label = _root_label(idx, fn, st, target)
        prev = idx.roots.get(target)
        if prev is None:
            idx.roots[target] = Root(target, label, multi)
        elif multi:
            prev.multi = True
    api_entries = [q for q in sorted(idx.functions)
                   if idx.functions[q].api]
    for q in api_entries:
        idx.roots.setdefault(q, Root(q, "api", True))

    # closure with the lifecycle cut: start()/stop() run single-threaded
    # around spawn/join, so roots neither land on nor flow through them
    for key in sorted(idx.roots):
        seen: set[str] = set()
        frontier = [key]
        while frontier:
            q = frontier.pop()
            if q in seen:
                continue
            fn = idx.functions.get(q)
            if fn is None or fn.lifecycle:
                continue
            seen.add(q)
            idx.fn_roots.setdefault(q, set()).add(key)
            for callee, _line, _held in fn.calls:
                frontier.append(callee)

    for key in sorted(idx.roots):
        for q, rts in idx.fn_roots.items():
            if key not in rts:
                continue
            fn = idx.functions[q]
            if any(re.search(r"\bepoll_wait\s*\(", st.text)
                   for st in fn.statements):
                idx.reactor_roots.add(key)
                break


def _compute_handoffs(idx: ConcurrencyIndex,
                      inbox_members: set[tuple[str, str]]) -> None:
    """Handoff functions: mutate an inbox member under a lock AND wake
    the reactor (eventfd write / a wake-named callee) — the documented
    inbox/eventfd edge."""
    for q in sorted(idx.functions):
        fn = idx.functions[q]
        mutates = any((a.cls, a.member) in inbox_members and a.write
                      and a.locks for a in fn.accesses)
        if not mutates:
            continue
        wakes = any(re.search(
            r"\bwake\w*\s*\(|\w*wake\s*\(|\beventfd_write\s*\(|"
            r"notify_(?:one|all)\s*\(", st.text)
            for st in fn.statements)
        if not wakes:
            wakes = any("wake" in c.rsplit("::", 1)[-1]
                        for c, _l, _h in fn.calls)
        if wakes:
            idx.handoff_fns.add(q)


# -------------------------------------------------------------- members


def _class_spans(text: str) -> list[tuple[str, int, int]]:
    spans = []
    for m in _CLASS_RE.finditer(text):
        lead = text[max(0, m.start() - 8):m.start()]
        if lead.rstrip().endswith("enum"):
            continue
        ob = m.end() - 1
        spans.append((m.group(2), ob, _balanced(text, ob)))
    return spans


_ACCESS_LABEL_RE = re.compile(r"\b(?:public|private|protected)\s*:")
_RANKED_RE = re.compile(
    r"\b(?:dm::)?(?:Ordered)?Mutex\s+(\w+)\s*\{\s*(kRank\w+)")
_DM_RANKED_RE = re.compile(r"\bDM_RANKED\s*\(\s*(\w+)\s*,\s*(kRank\w+)")
_PLAIN_MUTEX_RE = re.compile(
    r"\b(?:(?:dm::)?(?:Ordered)?Mutex|std::(?:recursive_|shared_|timed_)?"
    r"mutex|pthread_mutex_t)\s+(\w+)")
_CV_RE = re.compile(r"\bstd::condition_variable(?:_any)?\s+(\w+)")
_THREAD_RE = re.compile(
    r"\bstd::(?:vector\s*<\s*std::)?(?:thread|jthread)\s*>?\s+(\w+)")


def _blank_regions(text: str, opens: str, closes: str) -> str:
    out = list(text)
    depth = 0
    for i, c in enumerate(text):
        if c in opens:
            depth += 1
            out[i] = " "
        elif c in closes:
            depth -= 1
            out[i] = " "
        elif depth > 0:
            out[i] = " "
    return "".join(out)


def _parse_member_decl(idx: ConcurrencyIndex, cls: str, decl: str,
                       rel: str, line: int) -> None:
    d = _ACCESS_LABEL_RE.sub(" ", decl).strip()
    if not d or d.startswith(("using ", "typedef ", "friend ",
                              "static_assert", "template")):
        return
    members = idx.classes.setdefault(cls, {})

    m = _RANKED_RE.search(d) or _DM_RANKED_RE.search(d)
    if m:
        members[m.group(1)] = CMember(cls, m.group(1), "mutex",
                                      m.group(2), rel, line, d[:60])
        return
    m = _CV_RE.search(d)
    if m:
        members[m.group(1)] = CMember(cls, m.group(1), "cv", None, rel,
                                      line, d[:60])
        return
    m = _THREAD_RE.search(d)
    if m:
        members[m.group(1)] = CMember(cls, m.group(1), "thread", None,
                                      rel, line, d[:60])
        return
    m = _PLAIN_MUTEX_RE.search(d)
    if m:
        members[m.group(1)] = CMember(cls, m.group(1), "mutex", None,
                                      rel, line, d[:60])
        return
    if "std::atomic" in d:
        pos = d.find("std::atomic")
        i = d.find("<", pos)
        if i > 0:
            depth = 0
            while i < len(d):
                if d[i] == "<":
                    depth += 1
                elif d[i] == ">":
                    depth -= 1
                    if depth == 0:
                        break
                i += 1
            tail = d[i + 1:]
            for part in _split_commas(_blank_regions(tail, "{", "}")):
                nm = re.match(r"([A-Za-z_]\w*)", part.strip())
                if nm:
                    members[nm.group(1)] = CMember(
                        cls, nm.group(1), "atomic", None, rel, line,
                        d[:60])
        return
    # plain data members: blank templates/initializers, then the last
    # identifier of each comma declarator is the name
    flat = _blank_regions(d, "<", ">")
    flat = _blank_regions(flat, "{", "}")
    flat = re.sub(r"\[[^\]]*\]", " ", flat)
    if "(" in flat:
        return  # member function declaration / function pointer
    parts = _split_commas(flat)
    if not parts:
        return
    first = parts[0]
    nm = re.search(r"([A-Za-z_]\w*)\s*(?:=[^,]*)?$", first)
    if not nm:
        return
    name = nm.group(1)
    type_text = first[:nm.start()].strip()
    toks = re.findall(r"[A-Za-z_][\w:]*", type_text)
    if not toks or name in _CALL_KEYWORDS or \
            type_text.rstrip().endswith(("return", "goto")):
        return
    members[name] = CMember(cls, name, "data", None, rel, line,
                            type_text[:60])
    for part in parts[1:]:
        nm = re.search(r"([A-Za-z_]\w*)\s*(?:=[^,]*)?$", part)
        if nm:
            members[nm.group(1)] = CMember(cls, nm.group(1), "data",
                                           None, rel, line,
                                           type_text[:60])


def _members_of(idx: ConcurrencyIndex, cls: str, text: str, start: int,
                end: int, rel: str,
                inner_spans: list[tuple[str, int, int]]) -> None:
    i = start
    buf_start = start
    while i < end:
        c = text[i]
        if c == ";":
            decl = text[buf_start:i]
            ds = buf_start + (len(decl) - len(decl.lstrip()))
            _parse_member_decl(idx, cls, decl.strip(), rel,
                               _line_of(text, ds))
            buf_start = i + 1
            i += 1
        elif c == "{":
            chunk = text[buf_start:i]
            if re.search(r"\)\s*(?:const\b|noexcept\b|override\b|"
                         r"final\b|\s|->\s*[\w:<>&*\s]+?)*$", chunk) or \
                    _INIT_LIST_RE.search(chunk) or \
                    re.search(r"\b(?:class|struct|enum|union)\b", chunk):
                i = _balanced(text, i)
                buf_start = i
            else:
                i = _balanced(text, i)  # brace initializer: keep in buf
        else:
            i += 1


# ----------------------------------------------------------- statements


_INLINE_COND_RE = re.compile(r"\b(?:if|while)\s*\((.*)\)", re.DOTALL)


def _split_statements(fn: CFunction, body: str, base: int, text: str,
                      counter: list) -> None:
    stack: list[int] = []
    cond_stack: list[str] = []
    buf_start = 0
    paren = 0
    n = len(body)

    def emit(upto: int) -> None:
        chunk = body[buf_start:upto]
        stripped = chunk.strip()
        if not stripped:
            return
        start = buf_start + (len(chunk) - len(chunk.lstrip()))
        st = CStmt(_line_of(text, base + start), stripped, tuple(stack),
                   [c for c in cond_stack if c],
                   (base + start, base + upto))
        im = _INLINE_COND_RE.search(stripped)
        if im and not stripped.rstrip().endswith(")"):
            st.conds.append(im.group(1))
        fn.statements.append(st)

    i = 0
    while i < n:
        c = body[i]
        if c == "(":
            paren += 1
        elif c == ")":
            paren = max(0, paren - 1)
        elif c == ";" and paren == 0:
            emit(i)
            buf_start = i + 1
        elif c == "{":
            head = body[buf_start:i].strip()
            emit(i)
            counter[0] += 1
            bid = counter[0]
            cm = re.search(r"\b(?:if|while|for|switch)\s*\((.*)\)\s*$",
                           head, re.DOTALL)
            cond_stack.append(cm.group(1) if cm else "")
            stack.append(bid)
            fn.block_heads[bid] = head
            buf_start = i + 1
            paren = 0
        elif c == "}":
            emit(i)
            if stack:
                stack.pop()
                cond_stack.pop()
            buf_start = i + 1
            paren = 0
        i += 1
    emit(n)


def _carve_lambdas(idx: ConcurrencyIndex, parent: CFunction, body: str,
                   base: int, text: str, counter: list,
                   lambdas_by_start: dict) -> str:
    pos = 0
    while True:
        m = _LAMBDA_RE.search(body, pos)
        if not m:
            return body
        ob = m.end() - 1
        end = _balanced(body, ob)
        name_m = re.search(r"(?:auto|const\s+auto)\s*&?\s*(\w+)\s*=\s*$",
                           body[:m.start()])
        idx._lambda_seq += 1
        line = _line_of(text, base + m.start())
        short = name_m.group(1) if name_m else f"lambda@{line}"
        qname = f"{parent.qname}::{short}"
        if qname in idx.functions:
            qname = f"{parent.qname}::{short}#{idx._lambda_seq}"
        lam = CFunction(qname, parent.cls, short, parent.rel, line,
                        base + m.start(), base + end,
                        "(" + (m.group(1) or "") + ")",
                        is_lambda=True, parent=parent.qname)
        inner = body[ob + 1:end - 1]
        inner = _carve_lambdas(idx, lam, inner, base + ob + 1, text,
                               counter, lambdas_by_start)
        _split_statements(lam, inner, base + ob + 1, text, counter)
        idx.functions[qname] = lam
        idx.by_short.setdefault(short, []).append(qname)
        lambdas_by_start[base + m.start()] = qname
        blanked = re.sub(r"[^\n]", " ", body[m.start():end])
        body = body[:m.start()] + blanked + body[end:]
        pos = end


# --------------------------------------------------------------- driver


def native_files(native_dir: Path) -> list[Path]:
    return sorted(native_dir.glob("*.h")) + sorted(native_dir.glob("*.cc"))


def discover_native_files(files) -> list[Path]:
    """cache_extra_inputs body shared by the three passes: the native
    sources whose stat triples join each rule's cache key. Discovery
    mirrors the passes' anchoring — the real tree via
    ``demodel_tpu/utils/env.py``, fixtures via the
    ``concurrency-native=`` pragma."""
    dirs: list[Path] = []
    for p in files:
        path = Path(p)
        posix = path.as_posix()
        if posix.endswith("demodel_tpu/utils/env.py"):
            root = Path(posix[: -len("demodel_tpu/utils/env.py")] or ".")
            dirs.append(root / "native")
            continue
        try:
            head = path.read_text(encoding="utf-8", errors="replace")[:4096]
        except OSError:
            continue
        pm = PRAGMA_RE.search(head)
        if pm:
            dirs.append(path.parent / pm.group(1))
    out: list[Path] = []
    for d in dirs:
        if d.is_dir():
            out.extend(native_files(d))
    return out


_INDEX_CACHE: dict[tuple, ConcurrencyIndex] = {}


def build_index(native_dir: Path, prefix: str) -> ConcurrencyIndex | None:
    """Build (or fetch the memoized) concurrency index for one native
    dir. Returns None when the dir has no indexable sources."""
    paths = [p for p in native_files(native_dir)
             if p.name not in EXCLUDED_FILES]
    rank_paths = native_files(native_dir)
    sig = tuple((p.name, p.stat().st_mtime_ns, p.stat().st_size)
                for p in rank_paths)
    key = (str(native_dir.resolve()), prefix, sig)
    if key in _INDEX_CACHE:
        return _INDEX_CACHE[key]
    if not paths:
        return None

    idx = ConcurrencyIndex()
    texts: list[tuple[str, str]] = []   # (rel, stripped text)
    all_texts: list[str] = []           # rank-usage census, every file
    api_spans: dict[str, list] = {}
    file_class_spans: dict[str, list] = {}
    counter = [0]

    # ranks come from EVERY file (lock_order.h included)
    for p in rank_paths:
        try:
            raw = p.read_text(encoding="utf-8", errors="replace")
        except OSError:
            continue
        text = _strip_pp(strip_code(raw))
        rel = f"{prefix}{p.name}"
        for m in RANK_RE.finditer(text):
            idx.ranks.setdefault(
                m.group(1), (int(m.group(2)), rel, _line_of(text, m.start())))
        if p.name not in EXCLUDED_FILES:
            texts.append((rel, text))
        all_texts.append(text)
    for name in idx.ranks:
        uses = sum(len(re.findall(r"\b%s\b" % re.escape(name), t))
                   for t in all_texts)
        idx.rank_uses[name] = uses - 1  # minus the definition itself

    # pass 1: classes and members
    for rel, text in texts:
        spans = _class_spans(text)
        file_class_spans[rel] = spans
        ex = []
        for m in re.finditer(r'extern\s*"[^"\n]*"\s*\{', text):
            ob = m.end() - 1
            ex.append((ob, _balanced(text, ob)))
        api_spans[rel] = ex
        for cls, ob, end in spans:
            inner = [s for s in spans if s[1] > ob and s[2] <= end]
            _members_of(idx, cls, text, ob + 1, end - 1, rel, inner)

    # pointer/reference member types (Store *store_ → Store)
    for cls, mems in idx.classes.items():
        for name, mem in mems.items():
            tm = re.match(r"(?:const\s+)?([A-Z]\w*)\s*[*&]", mem.type_text)
            if tm and tm.group(1) in idx.classes:
                idx.member_types[(cls, name)] = tm.group(1)

    # pass 2: functions (+ carved lambdas)
    lambdas_by_start: dict[str, dict] = {}
    for rel, text in texts:
        lambdas_by_start[rel] = {}
        spans = file_class_spans[rel]
        pos = 0
        while True:
            fm = _FN_OPEN_RE.search(text, pos)
            if not fm:
                break
            ob = fm.end() - 1
            close = text.rfind(")", fm.start(), ob + 1)
            ilm = _INIT_LIST_RE.search(text[max(0, ob - 2000):ob])
            if ilm:
                close = max(0, ob - 2000) + ilm.start()
            name = _match_name(text, close)
            if not name or name.rsplit("::", 1)[-1] in _CALL_KEYWORDS:
                pos = fm.end()
                continue
            # inline destructors: _match_name drops the leading ~
            nstart = text.rfind(name, 0, close)
            if nstart > 0 and text[nstart - 1] == "~" \
                    and "~" not in name:
                name = "~" + name
            enclosing = None
            for cls, cob, cend in spans:
                if cob < fm.start() < cend:
                    if enclosing is None or cob > enclosing[1]:
                        enclosing = (cls, cob)
            if "::" in name.replace("::~", "~"):
                qname = name
                cls: str | None = name.rsplit("::", 1)[0]
            elif enclosing:
                cls = enclosing[0]
                qname = f"{cls}::{name}"
            else:
                cls = None
                qname = name
            end = _balanced(text, ob)
            if qname in idx.functions:
                qname = f"{qname}#{_line_of(text, fm.start())}"
            hstart = text.rfind("\n", 0, nstart) + 1
            fn = CFunction(qname, cls, name.rsplit("::", 1)[-1], rel,
                           _line_of(text, fm.start()), fm.start(), end,
                           text[hstart:ob])
            fn.api = any(s <= fm.start() < e for s, e in api_spans[rel])
            body = text[ob + 1:end - 1]
            body = _carve_lambdas(idx, fn, body, ob + 1, text, counter,
                                  lambdas_by_start[rel])
            _split_statements(fn, body, ob + 1, text, counter)
            idx.functions[qname] = fn
            idx.by_short.setdefault(fn.short, []).append(qname)
            pos = end

    # pass 3: per-function analysis
    for q in sorted(idx.functions):
        fn = idx.functions[q]
        fn.lifecycle = _is_lifecycle(fn)
        _collect_locals(idx, fn)
    for q in sorted(idx.functions):
        fn = idx.functions[q]
        _compute_guards(idx, fn)
    for q in sorted(idx.functions):
        fn = idx.functions[q]
        _compute_calls(idx, fn)
        _compute_accesses(idx, fn)
    for q in sorted(idx.functions):
        for callee, _line, held in idx.functions[q].calls:
            idx.callers.setdefault(callee, []).append((q, held))

    merged_lambda_starts: dict = {}
    for rel in lambdas_by_start:
        merged_lambda_starts.update(lambdas_by_start[rel])
    _compute_roots(idx, merged_lambda_starts)

    # inbox detection: a member the reactor closure drains via swap
    inbox_members: set[tuple[str, str]] = set()
    for root in sorted(idx.reactor_roots):
        for q, rts in sorted(idx.fn_roots.items()):
            if root not in rts:
                continue
            fn = idx.functions[q]
            for st in fn.statements:
                for sm in re.finditer(
                        r"\b\w+\s*\.\s*swap\s*\(\s*(\w+)\s*\)|"
                        r"\b(\w+)\s*\.\s*swap\s*\(", st.text):
                    name = sm.group(1) or sm.group(2)
                    mem = _member_lookup(idx, fn, None, name)
                    if mem is not None and mem.kind == "data":
                        inbox_members.add((mem.cls, mem.name))
    idx.inbox_members = inbox_members
    _compute_handoffs(idx, inbox_members)

    _INDEX_CACHE[key] = idx
    if len(_INDEX_CACHE) > 8:
        _INDEX_CACHE.pop(next(iter(_INDEX_CACHE)))
    return idx


def fmt_locks(locks: frozenset) -> str:
    if not locks:
        return "NO lock"
    return "{" + ", ".join(sorted(locks)) + "}"


class NativeAnchorMixin:
    """Shared anchoring for the three concurrency passes: the real tree
    activates via ``demodel_tpu/utils/env.py`` → ``<root>/native``;
    fixtures via a ``# demodel: concurrency-native=<dir>`` pragma."""

    @classmethod
    def cache_extra_inputs(cls, files) -> list:
        return discover_native_files(files)

    def __init__(self) -> None:
        super().__init__()
        self._native_dirs: list[tuple[Path, str]] = []

    def visit(self, ctx):
        pm = PRAGMA_RE.search(ctx.source)
        if pm:
            self._native_dirs.append(
                (Path(ctx.path).resolve().parent / pm.group(1),
                 ctx.rel.rsplit("/", 1)[0] + "/" + pm.group(1) + "/"
                 if "/" in ctx.rel else pm.group(1) + "/"))
        elif ctx.rel == "demodel_tpu/utils/env.py":
            root = Path(str(Path(ctx.path).resolve())[: -len(ctx.rel)]) \
                if str(Path(ctx.path).resolve()).endswith(ctx.rel) \
                else Path.cwd()
            self._native_dirs.append((root / "native", "native/"))
        return iter(())

    def each_index(self):
        seen: set[Path] = set()
        for native_dir, prefix in self._native_dirs:
            if native_dir in seen or not native_dir.is_dir():
                continue
            seen.add(native_dir)
            idx = build_index(native_dir, prefix)
            if idx is not None:
                yield idx
