"""Regex-level native index: function bodies + obligation events.

surface-parity's extractor reads a handful of named definitions out of
``native/*.{h,cc}``; the obligation rule needs more — every function
body, with enough statement structure to run the same
acquire/release/transfer discipline the Python plane gets from the AST.
This stays deliberately clang-free: comments and string literals are
blanked (offsets preserved), function bodies are found by brace
matching behind a ``) {`` opener, and statements are split on
``;``/``{``/``}`` with a condition stack so an early ``return`` knows
which ``if`` guards it.

RAII is a first-class discharge: a ``unique_ptr``/``lock_guard``/
``absl::Cleanup``-shaped wrapper on the acquire statement (or later
adoption of the value) settles the obligation. Everything the regex
level cannot prove — the value passed to another function, stored to a
member, returned — is an ownership transfer and stays silent, the same
no-speculative-edges posture as the Python index.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterator

# tokens whose presence on a statement means the resource is owned by a
# scope guard — destructor discharges the obligation
RAII_RE = re.compile(
    r"\b(?:unique_ptr|shared_ptr|lock_guard|unique_lock|scoped_lock|"
    r"Cleanup|Defer|ScopeGuard|ScopedFd|FdCloser)\b")

_KEYWORDS = {"if", "while", "for", "switch", "catch", "return", "sizeof",
             "defined", "assert", "static_assert", "alignof", "decltype"}

_FN_OPEN_RE = re.compile(
    r"\)\s*(?:const\b|noexcept\b|override\b|final\b|\s|->\s*[\w:<>&*\s]+?)*\{")

_INLINE_GUARD_RE = re.compile(
    r"\bif\s*\((?P<cond>.*)\)\s*(?P<tail>return\b|throw\b|goto\b|"
    r"continue\b|break\b)", re.DOTALL)

_EXIT_RE = re.compile(r"\b(return|throw|goto)\b")


def strip_code(text: str) -> str:
    """Blank comments, string and char literals — offsets preserved so
    line numbers computed over the result match the original."""

    out = []
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c == "/" and i + 1 < n and text[i + 1] == "/":
            j = text.find("\n", i)
            j = n if j < 0 else j
            out.append(" " * (j - i))
            i = j
        elif c == "/" and i + 1 < n and text[i + 1] == "*":
            j = text.find("*/", i + 2)
            j = n if j < 0 else j + 2
            out.append(re.sub(r"[^\n]", " ", text[i:j]))
            i = j
        elif c in "\"'":
            j = i + 1
            while j < n and text[j] != c:
                j += 2 if text[j] == "\\" else 1
            j = min(j + 1, n)
            out.append(c + " " * (j - i - 2) + (c if j - i >= 2 else ""))
            i = j
        else:
            out.append(c)
            i += 1
    return "".join(out)


@dataclass
class Statement:
    line: int
    text: str
    #: conditions of every enclosing block inside the function (plus the
    #: inline guard when the statement is `if (c) return;`)
    conds: list = field(default_factory=list)


@dataclass
class NativeFunction:
    name: str
    rel: str
    line: int
    body: str
    statements: list = field(default_factory=list)


def _match_name(text: str, close_paren: int) -> str:
    """The identifier before the ``(`` matching ``)`` at close_paren."""
    depth = 0
    i = close_paren
    while i >= 0:
        if text[i] == ")":
            depth += 1
        elif text[i] == "(":
            depth -= 1
            if depth == 0:
                break
        i -= 1
    if i < 0:
        return ""
    m = re.search(r"([A-Za-z_][\w:~]*)\s*$", text[:i])
    return m.group(1) if m else ""


def _balanced(text: str, open_brace: int) -> int:
    """Offset just past the ``}`` matching ``{`` at open_brace."""
    depth = 0
    for i in range(open_brace, len(text)):
        if text[i] == "{":
            depth += 1
        elif text[i] == "}":
            depth -= 1
            if depth == 0:
                return i + 1
    return len(text)


def _line_of(text: str, pos: int) -> int:
    return text.count("\n", 0, pos) + 1


def _statements(body: str, base_pos: int, text: str) -> list:
    """Flat statement list with per-statement condition stacks."""
    out: list[Statement] = []
    stack: list[str] = []
    pos = 0
    for m in re.finditer(r"[;{}]", body):
        chunk = body[pos:m.start()]
        stripped = chunk.strip()
        ch = m.group()
        line = _line_of(text, base_pos + pos + (len(chunk) - len(chunk.lstrip())))
        if ch == ";":
            if stripped:
                st = Statement(line, stripped, list(stack))
                g = _INLINE_GUARD_RE.search(stripped)
                if g:
                    st.conds.append(g.group("cond"))
                out.append(st)
        elif ch == "{":
            cm = re.search(r"\b(?:if|while|for|switch)\s*\((.*)\)\s*$",
                           stripped, re.DOTALL)
            stack.append(cm.group(1) if cm else "")
            if stripped and not cm:
                # `do {`, `else {`, struct literals — opaque block
                pass
        else:  # "}"
            if stripped:
                out.append(Statement(line, stripped, list(stack)))
            if stack:
                stack.pop()
        pos = m.end()
    return out


def extract_functions(path: Path, rel: str) -> Iterator[NativeFunction]:
    try:
        raw = path.read_text(encoding="utf-8", errors="replace")
    except OSError:
        return
    text = strip_code(raw)
    pos = 0
    while True:
        m = _FN_OPEN_RE.search(text, pos)
        if not m:
            return
        open_brace = m.end() - 1
        name = _match_name(text, text.rfind(")", m.start(), open_brace + 1))
        if not name or name.rsplit("::", 1)[-1] in _KEYWORDS:
            pos = m.end()
            continue
        end = _balanced(text, open_brace)
        body = text[open_brace + 1:end - 1]
        fn = NativeFunction(name, rel, _line_of(text, m.start()), body)
        fn.statements = _statements(body, open_brace + 1, text)
        yield fn
        pos = end


# ----------------------------------------------------------- resource pairs


@dataclass(frozen=True)
class NativePair:
    kind: str
    label: str
    acquire_re: re.Pattern
    release_token: str          # bare callee name of the release
    #: "result" — track the assigned variable; "arg" — track the acquire
    #: call's first argument text (key-matched pins/registrations)
    entity: str = "result"
    #: only analyze functions that call the release at least once —
    #: resources legitimately held across functions (session pins,
    #: epoll registrations) otherwise drown the rule in false leaks
    needs_local_release: bool = False
    #: skip the "never released anywhere" check (pairs whose release
    #: legitimately lives in another function)
    check_missing: bool = True


NATIVE_PAIRS = (
    NativePair("mmap", "mmap mapping (release: munmap)",
               re.compile(r"(?<![\w.])mmap\s*\("), "munmap"),
    NativePair("fd", "file descriptor (release: close)",
               re.compile(r"(?<![\w.:])(?:::\s*)?open\s*\("), "close"),
    NativePair("ssl", "SSL handle (release: SSL_free)",
               re.compile(r"\bSSL_new\s*\("), "SSL_free"),
    NativePair("hot-pin", "hot-tier pin (release: hot_release)",
               re.compile(r"\bhot_acquire\s*\("), "hot_release",
               entity="arg", needs_local_release=True, check_missing=False),
    NativePair("epoll", "epoll registration (release: EPOLL_CTL_DEL)",
               re.compile(r"\bepoll_ctl\s*\(\s*[^,]+,\s*EPOLL_CTL_ADD"),
               "EPOLL_CTL_DEL", entity="arg", needs_local_release=True,
               check_missing=False),
    # splice-tunnel pipe pairs (reactor writer plane): the fd array is
    # the acquire argument; ownership usually transfers into a
    # TunnelState closed elsewhere, so only a function that closes the
    # array locally is held to the no-early-exit rule
    NativePair("pipe", "splice pipe pair (release: close)",
               re.compile(r"\bpipe2?\s*\("), "close",
               entity="arg", needs_local_release=True, check_missing=False),
)


def _first_arg(text: str, call_end: int) -> str:
    """First top-level argument of the call whose ``(`` is at
    call_end-1 — the key a pin/registration is matched on."""
    depth, i, start = 1, call_end, call_end
    while i < len(text) and depth:
        c = text[i]
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
        elif c == "," and depth == 1:
            break
        i += 1
    return re.sub(r"\s+", "", text[start:i])


def _epoll_fd_arg(stmt: str) -> str:
    """The fd (third) argument of an epoll_ctl ADD statement."""
    m = re.search(r"epoll_ctl\s*\(([^;]*)", stmt)
    if not m:
        return ""
    parts = [p.strip() for p in m.group(1).split(",")]
    return re.sub(r"\s+", "", parts[2]) if len(parts) >= 3 else ""


@dataclass
class NativeObligation:
    """One native acquire with its locally-decided fate — mirrors the
    Python plane's ObligationSite closely enough for one shared rule."""

    kind: str
    label: str
    rel: str
    line: int
    entity: str
    fn_name: str
    #: (line, stmt text) of an unguarded early exit between the acquire
    #: and the function's release of the entity
    leak_exit: tuple | None = None
    #: nothing in the function releases, stores, returns, RAII-adopts,
    #: or forwards the entity
    never_settled: bool = False


def _entity_in(stmt: str, entity: str) -> bool:
    return re.search(r"(?<![\w.])%s\b" % re.escape(entity), stmt) is not None


def _bound_var(stmt: str, acq: re.Match) -> str:
    head = stmt[:acq.start()]
    m = re.search(r"([A-Za-z_]\w*)\s*=\s*[^=]*$", head)
    return m.group(1) if m else ""


def _member_store(stmt: str, entity: str) -> bool:
    pat = r"(?:\w+_|[\w\)\]]+(?:\.|->)\w+)\s*=\s*[^=]*(?<![\w.])%s\b" % \
        re.escape(entity)
    return re.search(pat, stmt) is not None


#: callees that USE a descriptor/pointer without taking ownership —
#: passing the entity here is not a transfer, so an early exit after a
#: failed pwrite still counts as the leak it is
_NON_OWNING = {
    "read", "write", "pread", "pwrite", "readv", "writev", "lseek",
    "fstat", "stat", "ftruncate", "fallocate", "fsync", "fdatasync",
    "flock", "fcntl", "ioctl", "msync", "madvise", "mprotect", "memcpy",
    "memcmp", "memmove", "dup", "dup2", "posix_fadvise", "mmap",
    "CHECK", "assert", "printf", "fprintf", "snprintf", "perror",
}


def _close_of(text: str, open_paren: int) -> int:
    depth = 0
    for i in range(open_paren, len(text)):
        if text[i] == "(":
            depth += 1
        elif text[i] == ")":
            depth -= 1
            if depth == 0:
                return i
    return len(text)


def _passed_to_call(stmt: str, entity: str) -> bool:
    """Entity handed to a callee that might take ownership."""
    for m in re.finditer(r"([A-Za-z_][\w:]*)\s*\(", stmt):
        name = m.group(1).rsplit("::", 1)[-1]
        if name in _NON_OWNING or name in _KEYWORDS:
            continue
        inner = stmt[m.end():_close_of(stmt, m.end() - 1)]
        if re.search(r"(?<![\w.])%s\b" % re.escape(entity), inner):
            return True
    return False


def scan_function(fn: NativeFunction) -> Iterator[NativeObligation]:
    body_has = {p.kind: p.release_token in fn.body for p in NATIVE_PAIRS}
    stmts = fn.statements
    for si, st in enumerate(stmts):
        for pair in NATIVE_PAIRS:
            acq = pair.acquire_re.search(st.text)
            if acq is None:
                continue
            if pair.needs_local_release and not body_has[pair.kind]:
                continue
            if RAII_RE.search(st.text):
                continue  # scope guard adopts it on the spot
            resvar = _bound_var(st.text, acq)
            if pair.entity == "arg":
                if pair.kind == "epoll":
                    entity = _epoll_fd_arg(st.text)
                else:
                    entity = _first_arg(st.text, acq.end())
                if not entity:
                    continue
            else:
                entity = resvar
                if not entity:
                    # `return mmap(...)` / `use(SSL_new(...))` — the
                    # value moved somewhere we cannot track: transfer
                    continue
            # guards on an arg-carried pin test the RESULT variable
            # (`if (!m) return` after `m = hot_acquire(key, ...)`) —
            # acquire-failure exits must know both names
            guards = {entity} | ({resvar} if resvar else set())
            yield from _judge(fn, pair, entity, guards, st, stmts[si + 1:])


def _judge(fn: NativeFunction, pair: NativePair, entity: str,
           guards: set, acquire: Statement,
           rest: list) -> Iterator[NativeObligation]:
    release_rx = re.compile(r"\b%s\s*\(" % re.escape(pair.release_token))
    first_settle = None       # index into rest of release/transfer
    release_anywhere = False
    transfer_anywhere = False
    for i, st in enumerate(rest):
        if release_rx.search(st.text) and (
                pair.entity == "arg" and
                re.sub(r"\s+", "", st.text).find(entity) >= 0
                or pair.entity == "result" and _entity_in(st.text, entity)):
            release_anywhere = True
            if first_settle is None:
                first_settle = i
            continue
        if pair.entity != "result":
            continue
        if not _entity_in(st.text, entity):
            continue
        if (re.search(r"\breturn\b[^;]*(?<![\w.])%s\b" % re.escape(entity),
                      st.text)
                or _member_store(st.text, entity)
                or RAII_RE.search(st.text)
                or re.search(r"\bstd::move\s*\(\s*%s\b" % re.escape(entity),
                             st.text)
                or _passed_to_call(st.text, entity)):
            transfer_anywhere = True
            if first_settle is None:
                first_settle = i

    site = NativeObligation(pair.kind, pair.label, fn.rel, acquire.line,
                            entity, fn.name)
    if first_settle is None:
        if pair.check_missing and not release_anywhere \
                and not transfer_anywhere:
            site.never_settled = True
            yield site
        return
    if not release_anywhere:
        return  # settled by transfer: someone else's obligation now
    # early-exit check: an unguarded return/throw strictly before the
    # first release/transfer leaks the entity on that path
    for st in rest[:first_settle]:
        em = _EXIT_RE.search(st.text)
        if not em:
            continue
        if any(re.search(r"\breturn\b[^;]*(?<![\w.])%s\b" % re.escape(g),
                         st.text) for g in guards):
            continue  # returning the entity (or its pin) is a transfer
        if any(c and _entity_in(c, g) for c in st.conds for g in guards):
            continue  # guarded on the entity: acquire-failure path
        site.leak_exit = (st.line, st.text[:60])
        yield site
        return
