"""Obligation facts: must-release resource tracking per function.

The Infer/Pulse "must-call" shape, grafted onto the ProjectIndex: a
declared table of paired resources (budget tickets, flight leases,
store partial writers, fds, mmaps, streamed HTTP responses, spans) and
one bottom-up walk per function that records, for every acquire site,

- **acquires-obligation** — the resource kind, the bound entity, and
  the acquire line (the blame anchor);
- **releases-obligation** — where the entity's normal path settles: a
  release-method call (``close``/``commit``/``abort``/``finish``/…), a
  ``with`` entry, or ``os.close(fd)``;
- **transfers-ownership** — escapes that move the obligation to
  someone else: returned to the caller, stored into ``self``/a
  container/an alias, captured by a nested def, handed to a known
  owner-taking callable, or passed to a *resolved* project callee
  (recorded as a pending edge the pass composes through the call graph
  at bounded depth — a callee that provably drops the entity is NOT a
  transfer, and the blame lands back on the acquire site).

Path sensitivity is the protected-region check: may-raise statements
between the acquire and its first settle point must sit under a
``try`` whose ``finally`` or handler discharges the entity (or the
acquire must be a ``with`` item). Everything unresolved is
under-approximated in the silent direction — no speculative leaks —
mirroring the index's no-speculative-edges contract.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Iterator

from tools.analyze.core import dotted, walk_in_scope

if TYPE_CHECKING:  # pragma: no cover - typing only
    from tools.analyze.core import ModuleContext
    from tools.analyze.index import FunctionInfo, ProjectIndex

BUDGETISH_RE = re.compile(r"budget", re.IGNORECASE)
FLIGHTISH_RE = re.compile(r"flight", re.IGNORECASE)
STOREISH_RE = re.compile(r"store", re.IGNORECASE)
TRACEISH_RE = re.compile(r"trace|tracer", re.IGNORECASE)
KVISH_RE = re.compile(r"kv|pool", re.IGNORECASE)
ADMITISH_RE = re.compile(r"admission|admit|queue", re.IGNORECASE)

_HTTP_VERBS = {"get", "post", "put", "patch", "delete", "head", "request"}

#: callables that take OWNERSHIP of the argument (releasing it becomes
#: their problem) — passing the entity here settles the obligation
OWNER_TAKING = {"os.fdopen", "closing", "contextlib.closing"}

#: container methods: the entity now lives in a collection/queue whose
#: owner inherits the obligation (the streaming sink's ticket handoff)
CONTAINER_SINKS = {"append", "add", "put", "put_nowait", "setdefault",
                   "register", "push", "insert", "appendleft"}

#: calls that cannot realistically raise — excluded from the risk
#: region so a log line between acquire and release is not "a leak"
_SAFE_EXACT = {"len", "min", "max", "isinstance", "hasattr", "getattr",
               "int", "float", "str", "bytes", "bool", "id", "repr",
               "time.time", "time.monotonic", "time.perf_counter",
               "_tick", "print"}
_SAFE_PREFIXES = ("log.", "logger.", "logging.", "warnings.")


@dataclass(frozen=True)
class Resource:
    kind: str              # short id used in blame messages
    label: str             # human phrase naming the pair
    releases: frozenset    # method names that discharge the obligation
    carrier: str = "result"  # "result" (bound value) | "receiver"


_FD = Resource("fd", "os.open file descriptor (release: os.close)",
               frozenset({"close"}))
_MMAP = Resource("mmap", "mmap mapping (release: .close())",
                 frozenset({"close"}))
_WRITER = Resource(
    "store-writer",
    "store partial writer (release: .commit() or .abort())",
    frozenset({"commit", "abort", "close"}))
_FLIGHT = Resource(
    "flight", "single-flight lease (release: .finish() or .resign())",
    frozenset({"finish", "resign"}))
_BUDGET = Resource(
    "budget", "budget ticket (release: .release() or .abort())",
    frozenset({"release", "abort"}), carrier="receiver")
_RESPONSE = Resource(
    "response", "streamed HTTP response (release: .close())",
    frozenset({"close", "release_conn"}))
_SPAN = Resource("span", "span (release: .finish()/.end())",
                 frozenset({"finish", "end", "close"}))
_KV = Resource("kv-lease", "paged KV block lease (release: .free())",
               frozenset({"free"}))
_TICKET = Resource(
    "ticket", "generation admission ticket (release: .finish())",
    frozenset({"finish"}))

#: every release-ish method name any tracked resource recognizes — the
#: generic set used when judging how a callee treats a PARAMETER
ANY_RELEASE = frozenset().union(*(r.releases for r in (
    _FD, _MMAP, _WRITER, _FLIGHT, _BUDGET, _RESPONSE, _SPAN, _KV,
    _TICKET)))


def classify_acquire(call: ast.Call, recv_src: str,
                     resolved: str | None) -> Resource | None:
    """The resource a call acquires, or None. Recognition is
    receiver-shaped (name pattern or index-resolved class) — the same
    two levels the budget-charge summary already uses."""
    name = dotted(call.func) or ""
    if name == "os.open":
        return _FD
    if name == "mmap.mmap" or name.endswith(".mmap.mmap"):
        return _MMAP
    if not isinstance(call.func, ast.Attribute):
        return None
    attr = call.func.attr
    res_l = (resolved or "").lower()
    if attr in ("begin", "begin_ranged") and (
            STOREISH_RE.search(recv_src) or "store" in res_l):
        return _WRITER
    if attr == "lease" and (FLIGHTISH_RE.search(recv_src)
                            or "flight" in res_l):
        return _FLIGHT
    if attr in ("acquire", "charge") and (
            BUDGETISH_RE.search(recv_src) or "budget" in res_l):
        return _BUDGET
    if attr in _HTTP_VERBS:
        for kw in call.keywords:
            if kw.arg == "stream" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value is True:
                return _RESPONSE
    if attr in ("span", "start_span") and (
            TRACEISH_RE.search(recv_src) or "trace" in res_l):
        return _SPAN
    if attr == "alloc" and (KVISH_RE.search(recv_src) or "pool" in res_l):
        return _KV
    if attr == "admit" and (ADMITISH_RE.search(recv_src)
                            or "admission" in res_l):
        return _TICKET
    return None


@dataclass
class ObligationSite:
    """One acquire and everything local analysis learned about it."""

    kind: str
    label: str
    line: int
    acquire_src: str          # short source of the acquire expr
    entity: str               # bound name / receiver dotted text
    carrier: str
    #: ("discharge", line) | ("transfer", how, line) | None — the first
    #: normal-path settle point in source order
    settle: tuple | None = None
    #: resolved-callee handoffs seen before any definite settle:
    #: [(callee qname, callee param name, line)] — composed by the pass
    forwards: list = field(default_factory=list)
    #: unprotected may-raise statements inside the live region:
    #: [(line, src)] — each is a path where the entity leaks
    risky: list = field(default_factory=list)
    #: result-carried acquire whose value is thrown away on the spot
    discarded: bool = False
    #: leadership variable of a ``flight, leader = lease(...)`` unpack:
    #: statements guarded on it are follower paths — the lease is the
    #: LEADER's obligation, so those raises are not this site's leaks
    guard: str = ""


# --------------------------------------------------------------- events


def _recv_of(call: ast.Call) -> str:
    if isinstance(call.func, ast.Attribute):
        return dotted(call.func.value) or ""
    return ""


def _names_in_value(expr: ast.AST) -> Iterator[str]:
    """Names DIRECTLY carried by an expression (ownership moves with
    the value): a bare name, or names inside a tuple/list literal.
    ``v.digest()`` carries v's result, not v — deliberately excluded."""
    if isinstance(expr, ast.Name):
        yield expr.id
    elif isinstance(expr, (ast.Tuple, ast.List)):
        for e in expr.elts:
            if isinstance(e, ast.Name):
                yield e.id


def _param_of(info: "FunctionInfo", call: ast.Call,
              arg_node: ast.AST) -> str | None:
    """Which parameter of ``info`` this positional/keyword arg fills."""
    params = list(info.params)
    if params and params[0] in ("self", "cls"):
        params = params[1:]
    for i, a in enumerate(call.args):
        if a is arg_node:
            if any(isinstance(x, ast.Starred) for x in call.args[:i + 1]):
                return None
            return params[i] if i < len(params) else None
    for kw in call.keywords:
        if kw.value is arg_node:
            return kw.arg
    return None


class _FnScan:
    """One walk over a function body, shared by every entity analyzed
    in it: per-name ownership events, may-raise statements, and the
    try-structure needed for the protected-region check."""

    def __init__(self, ctx: "ModuleContext", fn: ast.AST,
                 index: "ProjectIndex") -> None:
        self.ctx = ctx
        self.fn = fn
        self.index = index
        #: name → [(line, kind, payload, node)] — kind in {"discharge",
        #: "transfer", "forward", "end"}; payload: method name /
        #: how / (callee q, param); node anchors the branch-arm check
        self.events: dict[str, list] = {}
        #: may-raise statements: [(line, node)]
        self.risky: list = []
        self._res = index.resolution.get(ctx.rel, {})
        self._walk()

    def _add(self, name: str, line: int, kind: str, payload=None,
             node: ast.AST | None = None) -> None:
        self.events.setdefault(name, []).append((line, kind, payload, node))

    def _is_safe_call(self, call: ast.Call) -> bool:
        name = dotted(call.func) or ""
        return name in _SAFE_EXACT or name.startswith(_SAFE_PREFIXES)

    def _note_call(self, call: ast.Call) -> None:
        name = dotted(call.func) or ""
        recv = _recv_of(call)
        attr = call.func.attr if isinstance(call.func, ast.Attribute) \
            else ""
        # discharge: release-method on a dotted receiver
        if recv and attr in ANY_RELEASE:
            self._add(recv, call.lineno, "discharge", attr, call)
            # a release on self.<a>.<b> also discharges entity self.<a>?
            # no — keep identity exact (no speculative discharges)
        if name == "os.close" and call.args:
            tgt = dotted(call.args[0])
            if tgt:
                self._add(tgt, call.lineno, "discharge", "os.close", call)
        # entity handed off as an argument
        q = self._res.get(id(call))
        callee = self.index.functions.get(q) if q else None
        ctor = None
        if callee is None and name:
            ctor = self.index.resolve_class(self.ctx, name)
        for arg in list(call.args) + [k.value for k in call.keywords]:
            seed = arg.value if isinstance(arg, ast.Starred) else arg
            if not isinstance(seed, ast.Name):
                continue
            v = seed.id
            if name in OWNER_TAKING:
                self._add(v, call.lineno, "transfer", f"{name}()", call)
            elif attr in CONTAINER_SINKS:
                self._add(v, call.lineno, "transfer",
                          f"stored via .{attr}()", call)
            elif ctor is not None:
                self._add(v, call.lineno, "transfer",
                          f"owned by {ctor.rsplit('.', 1)[-1]}(...)", call)
            elif callee is not None:
                p = _param_of(callee, call, arg)
                if p is not None:
                    self._add(v, call.lineno, "forward", (q, p), call)

    def _walk(self) -> None:
        for node in walk_in_scope(self.fn):
            if isinstance(node, ast.Call):
                self._note_call(node)
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    expr = item.context_expr
                    if isinstance(expr, ast.Call) and (
                            dotted(expr.func) or "") in OWNER_TAKING \
                            and expr.args:
                        expr = expr.args[0]
                    tgt = dotted(expr)
                    if tgt:
                        self._add(tgt, node.lineno, "discharge", "with",
                                  node)
            elif isinstance(node, ast.Return) and node.value is not None:
                for v in _names_in_value(node.value):
                    self._add(v, node.lineno, "transfer", "returned", node)
            elif isinstance(node, (ast.Yield, ast.YieldFrom)) \
                    and getattr(node, "value", None) is not None:
                for v in _names_in_value(node.value):
                    self._add(v, node.lineno, "transfer", "yielded", node)
            elif isinstance(node, ast.Assign):
                for v in _names_in_value(node.value):
                    self._add(v, node.lineno, "transfer",
                              self._store_how(node), node)
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self._add(tgt.id, node.lineno, "end", None, node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.Lambda)):
                # a nested def capturing the entity owns it now
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Name):
                        self._add(sub.id, node.lineno, "transfer",
                                  "captured by a nested def", node)
            # ---- risk collection (leaf statements + branch tests)
            if isinstance(node, (ast.Expr, ast.Assign, ast.AugAssign,
                                 ast.AnnAssign, ast.Return)):
                if any(isinstance(c, ast.Call) and not self._is_safe_call(c)
                       for c in ast.walk(node)):
                    self.risky.append((node.lineno, node))
            elif isinstance(node, (ast.Raise, ast.Assert)):
                self.risky.append((node.lineno, node))
            elif isinstance(node, (ast.If, ast.While)):
                if any(isinstance(c, ast.Call) and not self._is_safe_call(c)
                       for c in ast.walk(node.test)):
                    self.risky.append((node.lineno, node))
            elif isinstance(node, ast.For):
                if any(isinstance(c, ast.Call) and not self._is_safe_call(c)
                       for c in ast.walk(node.iter)):
                    self.risky.append((node.lineno, node))
        for evs in self.events.values():
            evs.sort(key=lambda e: e[0])
        self.risky.sort(key=lambda r: r[0])

    @staticmethod
    def _store_how(node: ast.Assign) -> str:
        tgt = node.targets[0]
        if isinstance(tgt, ast.Attribute):
            return f"stored to {dotted(tgt) or 'an attribute'}"
        if isinstance(tgt, ast.Subscript):
            return "stored into a container"
        return "aliased"

    # ------------------------------------------------------ branch arms
    def _arms(self, node: ast.AST) -> dict:
        """id(If) → which arm (``body``/``orelse``) this node sits in,
        for every enclosing If up to the function."""
        arms: dict[int, str] = {}
        child = node
        cur = getattr(node, "_dm_parent", None)
        while cur is not None and child is not self.fn:
            if isinstance(cur, ast.If):
                if child in cur.body:
                    arms[id(cur)] = "body"
                elif child in cur.orelse:
                    arms[id(cur)] = "orelse"
            child = cur
            cur = getattr(cur, "_dm_parent", None)
        return arms

    def _exclusive(self, a: ast.AST, b: ast.AST | None) -> bool:
        """True when a and b sit in SIBLING arms of one If — a settle
        on the other arm of the acquire's branch never executes on the
        acquire's path and must not count."""
        if b is None:
            return False
        aa = self._arms(a)
        if not aa:
            return False
        bb = self._arms(b)
        return any(k in bb and bb[k] != v for k, v in aa.items())

    def _guarded_on(self, node: ast.AST, name: str) -> bool:
        """Is node under an If whose test reads ``name``?"""
        cur = getattr(node, "_dm_parent", None)
        while cur is not None and cur is not self.fn:
            if isinstance(cur, ast.If) and any(
                    isinstance(n, ast.Name) and n.id == name
                    for n in ast.walk(cur.test)):
                return True
            cur = getattr(cur, "_dm_parent", None)
        return False

    # ------------------------------------------------------ protection
    def _try_discharges(self, try_node: ast.Try, entity: str,
                        releases: frozenset) -> bool:
        """Does this try's finally/except discharge ``entity``?"""
        bodies = list(try_node.finalbody)
        for h in try_node.handlers:
            bodies.extend(h.body)
        for stmt in bodies:
            for sub in ast.walk(stmt):
                if not isinstance(sub, ast.Call):
                    continue
                if isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr in releases \
                        and (dotted(sub.func.value) or "") == entity:
                    return True
                if (dotted(sub.func) or "") == "os.close" and sub.args \
                        and (dotted(sub.args[0]) or "") == entity:
                    return True
        return False

    def _protected(self, node: ast.AST, entity: str,
                   releases: frozenset) -> bool:
        """Is an exception AT ``node`` guaranteed to discharge the
        entity (an enclosing try releases it in finally/except)? A
        statement inside an except handler is already a cleanup path —
        never flagged."""
        child = node
        cur = getattr(node, "_dm_parent", None)
        while cur is not None and cur is not self.fn:
            if isinstance(cur, ast.ExceptHandler):
                return True  # cleanup path, out of scope
            if isinstance(cur, ast.Try) and child in cur.body \
                    and self._try_discharges(cur, entity, releases):
                return True
            child = cur
            cur = getattr(cur, "_dm_parent", None)
        return False

    # -------------------------------------------------------- analysis
    def analyze(self, site: ObligationSite, releases: frozenset,
                acquire: ast.AST) -> None:
        """Fill ``site.settle``/``forwards``/``risky`` from the raw
        event stream: first settle in source order, skipping events in
        a branch arm the acquire's path can never reach."""
        evs = [e for e in self.events.get(site.entity, [])
               if e[0] > site.line or (e[0] == site.line and e[1] != "end")]
        settle = None
        for line, kind, payload, node in evs:
            if self._exclusive(acquire, node):
                continue
            if kind == "discharge" and (payload in releases
                                        or payload in ("with", "os.close")):
                settle = ("discharge", line)
                break
            if kind == "transfer":
                settle = ("transfer", payload, line)
                break
            if kind == "end":
                # rebound before any settle: a new epoch starts; stay
                # silent (under-approximation — no speculative leaks)
                settle = ("transfer", "rebound", line)
                break
            if kind == "forward":
                site.forwards.append((payload[0], payload[1], line))
        site.settle = settle
        end_line = settle[-1] if settle is not None else (
            site.forwards[0][2] if site.forwards else None)
        if end_line is None:
            return
        for line, node in self.risky:
            if not (site.line < line < end_line):
                continue
            if self._protected(node, site.entity, releases):
                continue
            if self._exclusive(acquire, node):
                continue
            if site.guard and self._guarded_on(node, site.guard):
                continue  # follower path of a leased flight
            src = self.ctx.lines[line - 1].strip() if \
                line <= len(self.ctx.lines) else ""
            site.risky.append((line, src[:60]))


# ------------------------------------------------------------ collection


def collect(ctx: "ModuleContext", fn: ast.AST, info: "FunctionInfo",
            index: "ProjectIndex") -> None:
    """Fill ``info.obligations`` / ``info.param_fate`` /
    ``info.released_receivers`` — the per-function summary facts."""
    res_map = index.resolution.get(ctx.rel, {})
    scan = _FnScan(ctx, fn, index)

    for node in walk_in_scope(fn):
        if not isinstance(node, ast.Call):
            continue
        recv = _recv_of(node)
        res = classify_acquire(node, recv, res_map.get(id(node)))
        if res is None:
            continue
        parent = getattr(node, "_dm_parent", None)
        # syntactic position decides the entity (or settles on the spot)
        if isinstance(parent, (ast.withitem,)):
            continue  # with acquire() as v: discharged by construction
        if isinstance(parent, ast.Call) and (
                dotted(parent.func) or "") in OWNER_TAKING:
            continue  # closing(acquire(...)): ownership moved
        site = ObligationSite(
            kind=res.kind, label=res.label, line=node.lineno,
            acquire_src=ctx.src(node)[:80], entity="", carrier=res.carrier)
        if res.carrier == "receiver":
            # no local settle here means the class/project discipline
            # decides (the pass's global released-receivers check)
            site.entity = recv
            scan.analyze(site, res.releases, node)
            info.obligations.append(site)
            continue
        # result-carried: how is the value bound?
        if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
            tgt = parent.targets[0]
            if isinstance(tgt, ast.Name):
                site.entity = tgt.id
            elif isinstance(tgt, ast.Tuple) and res.kind == "flight" \
                    and tgt.elts and isinstance(tgt.elts[0], ast.Name):
                site.entity = tgt.elts[0].id  # (flight, is_leader) unpack
                if len(tgt.elts) > 1 and isinstance(tgt.elts[1], ast.Name):
                    site.guard = tgt.elts[1].id
            elif isinstance(tgt, (ast.Attribute, ast.Subscript)):
                continue  # self.x = acquire(): stored, owner inherits
            else:
                continue
        elif isinstance(parent, ast.Return):
            continue  # returned directly: the caller inherits
        elif isinstance(parent, ast.Expr):
            site.discarded = True
            info.obligations.append(site)
            continue
        else:
            continue  # argument / comprehension / etc: out of scope
        scan.analyze(site, res.releases, node)
        info.obligations.append(site)

    # releases-obligation facts: receivers this function discharges
    # (the global discipline check for receiver-carried tickets)
    for name, evs in scan.events.items():
        if any(kind == "discharge" and payload not in ("with",)
               for _, kind, payload, _n in evs):
            info.released_receivers.add(name)
    # parameter fates: how this function treats an obligation handed to
    # it — collected for EVERY function so a caller's transfer can be
    # judged (a callee that provably drops the entity is the leak the
    # interprocedural contract pins back on the acquire site). A
    # definite event (release/keep/rebind) ANYWHERE outranks a soft
    # forward: `helper(v); v.close()` releases, whatever helper does.
    for p in info.params:
        if p in ("self", "cls"):
            continue
        fate = None
        fwd = None
        for line, kind, payload, _n in scan.events.get(p, []):
            if kind == "discharge":
                fate = ("released", line)
            elif kind == "transfer":
                fate = ("kept", payload, line)
            elif kind == "end":
                fate = ("kept", "rebound", line)
            elif fwd is None:
                fwd = ("forwarded", payload[0], payload[1], line)
            if fate is not None:
                break
        info.param_fate[p] = fate or fwd or ("dropped",)
