"""Rule passes. Importing this package registers every rule; add a new
pass by dropping a module here and importing it below."""

from tools.analyze.passes import (  # noqa: F401 — registration imports
    async_tasks,
    atomic_snapshot,
    excepts,
    guarded_field,
    hbm_budget,
    host_sync,
    jit_hygiene,
    json_shape,
    lock_io,
    lock_order,
    log_hygiene,
    metric_hygiene,
    native_guarded_field,
    native_lock_order,
    obligation_leak,
    reactor_ownership,
    surface_parity,
    swarm_policy,
    threads,
    wire_policy,
)
