"""orphaned-async-task: ``asyncio.create_task``/``ensure_future`` results
that nothing owns — completing the ``unjoined-thread`` family.

The event loop keeps only a WEAK reference to a task: a discarded
``create_task`` result can be garbage-collected mid-flight, and its
exception is never retrieved ("Task exception was never retrieved" at
interpreter shutdown, silent loss before that). Error paths are the same
trap one level up: a task created before an ``await`` that raises is
orphaned unless a ``finally``/handler cancels or awaits it.

A created task is OWNED (no finding) when, in the same scope, it is:

- awaited (``await t``), cancelled (``t.cancel()``), or gathered;
- passed to a call (``asyncio.wait(tasks)``, ``group.append(t)``) — the
  receiver can await it;
- stored (attribute/subscript/collection literal/comprehension),
  returned, or yielded.

Additionally, a name-bound task whose ONLY await sits after another
``await`` (a suspension that can raise) fires unless some enclosing
``try``'s handler or ``finally`` references the task — the
cancel-on-error-path discipline.

Deliberate fire-and-forget gets an inline
``# demodel: allow(orphaned-async-task)`` with a why.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.analyze.core import (
    Finding,
    ModuleContext,
    Pass,
    dotted,
    enclosing_function,
    register,
    walk_in_scope,
)


def _is_task_ctor(node: ast.Call) -> bool:
    name = dotted(node.func)
    return name is not None and (
        name.endswith("create_task") or name.endswith("ensure_future"))


def _scope_of(node: ast.AST, ctx: ModuleContext) -> ast.AST:
    fn = enclosing_function(node)
    return fn if fn is not None else ctx.tree


def _name_referenced(tree_part: list, name: str) -> bool:
    for stmt in tree_part:
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.Name) and sub.id == name:
                return True
    return False


def _events(scope: ast.AST, name: str) -> dict:
    """How a task-bound name is used inside ``scope``."""
    ev = {"owned": False, "awaited_at": None}
    for sub in walk_in_scope(scope):
        if isinstance(sub, ast.Await):
            val = sub.value
            if isinstance(val, ast.Name) and val.id == name:
                ev["owned"] = True
                if ev["awaited_at"] is None:
                    ev["awaited_at"] = sub.lineno
            # await gather(t, ...) handled by the call-arg clause below
        if isinstance(sub, ast.Call):
            if isinstance(sub.func, ast.Attribute) \
                    and isinstance(sub.func.value, ast.Name) \
                    and sub.func.value.id == name \
                    and sub.func.attr in ("cancel", "add_done_callback",
                                          "result", "exception"):
                ev["owned"] = True
            for arg in list(sub.args) + [kw.value for kw in sub.keywords]:
                if isinstance(arg, ast.Name) and arg.id == name:
                    ev["owned"] = True
                if isinstance(arg, ast.Starred) \
                        and isinstance(arg.value, ast.Name) \
                        and arg.value.id == name:
                    ev["owned"] = True
        if isinstance(sub, ast.Assign):
            for tgt in sub.targets:
                if (isinstance(tgt, (ast.Attribute, ast.Subscript))
                        and isinstance(sub.value, ast.Name)
                        and sub.value.id == name):
                    ev["owned"] = True
        if isinstance(sub, (ast.Return, ast.Yield, ast.YieldFrom)):
            val = sub.value
            if isinstance(val, ast.Name) and val.id == name:
                ev["owned"] = True
            if isinstance(val, (ast.Tuple, ast.List)):
                for elt in val.elts:
                    if isinstance(elt, ast.Name) and elt.id == name:
                        ev["owned"] = True
    return ev


@register
class OrphanedAsyncTaskPass(Pass):
    id = "orphaned-async-task"
    description = (
        "asyncio.create_task/ensure_future result discarded, never "
        "awaited/cancelled/stored, or not covered on error paths (weak-ref "
        "GC + swallowed exceptions)"
    )

    def visit(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not _is_task_ctor(node):
                continue
            parent = getattr(node, "_dm_parent", None)
            # bare statement: the loop's weak ref is the ONLY ref
            if isinstance(parent, ast.Expr):
                yield Finding(
                    ctx.rel, node.lineno, self.id,
                    "task reference discarded — the event loop holds only "
                    "a weak ref, so the task can be GC'd mid-flight and "
                    "its exception is never retrieved",
                )
                continue
            if not (isinstance(parent, ast.Assign)
                    and len(parent.targets) == 1
                    and isinstance(parent.targets[0], ast.Name)):
                # stored in a collection/arg/comprehension/attribute —
                # ownership moved somewhere that can await it
                continue
            name = parent.targets[0].id
            scope = _scope_of(node, ctx)
            ev = _events(scope, name)
            if not ev["owned"]:
                yield Finding(
                    ctx.rel, node.lineno, self.id,
                    f"task '{name}' is never awaited, gathered, cancelled, "
                    "or stored — orphaned the moment this scope exits",
                )
                continue
            f = self._error_path_orphan(ctx, scope, parent, name, ev)
            if f is not None:
                yield f

    def _error_path_orphan(self, ctx: ModuleContext, scope: ast.AST,
                           assign: ast.Assign, name: str,
                           ev: dict) -> Finding | None:
        """Awaited, but an intermediate ``await`` between creation and the
        task's own await can raise with nothing cancelling the task."""
        if ev["awaited_at"] is None:
            return None  # owned some other way (stored/gathered/cancelled)
        intermediate = None
        for sub in walk_in_scope(scope):
            if not isinstance(sub, ast.Await) or sub.lineno <= assign.lineno \
                    or sub.lineno >= ev["awaited_at"]:
                continue
            if isinstance(sub.value, ast.Name) and sub.value.id == name:
                continue
            # an await of something else, while our task is in flight
            intermediate = sub
            break
        if intermediate is None:
            return None
        # covered when ANY try enclosing the intermediate await references
        # the task in a handler or finally (cancel/await/gather)
        cur = getattr(intermediate, "_dm_parent", None)
        while cur is not None and cur is not scope:
            if isinstance(cur, ast.Try):
                guards = list(cur.finalbody)
                for h in cur.handlers:
                    guards.extend(h.body)
                if _name_referenced(guards, name):
                    return None
            cur = getattr(cur, "_dm_parent", None)
        return Finding(
            ctx.rel, intermediate.lineno, self.id,
            f"awaiting here can raise while task '{name}' is in flight — "
            f"no enclosing finally/except cancels it (created line "
            f"{assign.lineno})",
        )
