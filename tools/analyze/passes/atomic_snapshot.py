"""atomic-snapshot: check-then-act / torn-read detection.

The exact ``Telemetry.summary()`` bug (PR 9, finding 14): a method reads
ring state under one acquisition of ``self._lock``, releases it, then
re-acquires the SAME lock and combines state derived under the first
hold with state read under the second — a concurrent writer between the
holds makes the two halves describe different worlds, tearing the
"snapshot" the method claims to produce.

Model: every ``with <lock>:`` statement is a *region* of that lock, the
try/finally idiom — a bare statement-position ``X.acquire()`` whose next
sibling is a ``try:`` releasing the same lock in its ``finally:`` — is a
region over the ``try`` body, and every ``x = self.m(...)`` call whose
resolved callee's ``acquires-lock`` summary (through the call graph,
bounded) contains a lock is a region of that lock too (the hold happens
inside the callee on the method's behalf). A def-use edge that CROSSES region boundaries of one lock —
a name assigned inside region 1, not reassigned in between, consumed
inside a later region 2 of the same lock, in the same function — is the
finding; blame carries both holds.

Limits (documented in the README): the dataflow is name-based — state
carried between holds through ``self`` attributes or container mutation
is not tracked; call regions are recognized for ``self.<method>()``
receivers only (one instance, one lock identity — cross-object calls
would need alias facts the index deliberately does not speculate
about); a region re-entered inside itself (``with L: … with L:``) is
the lock-order pass's self-cycle, not a snapshot tear.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Iterator

from tools.analyze.core import (
    Finding,
    ModuleContext,
    Pass,
    enclosing_class,
    register,
    walk_in_scope,
)
from tools.analyze.index import lock_id


@dataclass
class _Region:
    lock: str
    node: ast.AST
    line: int
    end_line: int
    defs: set
    uses: set
    kind: str  # "with" | "call" | "acquire"


def _names(node: ast.AST, ctx_type) -> set[str]:
    out: set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ctx_type):
            out.add(sub.id)
    return out


def _next_sibling(stmt: ast.stmt) -> ast.AST | None:
    parent = getattr(stmt, "_dm_parent", None)
    if parent is None:
        return None
    for fname in ("body", "orelse", "finalbody"):
        seq = getattr(parent, fname, None)
        if isinstance(seq, list) and stmt in seq:
            i = seq.index(stmt)
            return seq[i + 1] if i + 1 < len(seq) else None
    return None


def _live_uses(node: ast.AST) -> set[str]:
    """Names LOADED in ``node`` whose first load precedes any store to
    the same name inside ``node`` — a region that rewrites a name before
    reading it (double-checked locking's re-read) consumes its OWN
    value, not state carried from an earlier hold."""
    first_load: dict[str, tuple[int, int]] = {}
    first_store: dict[str, tuple[int, int]] = {}
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Name):
            continue
        key = (sub.lineno, sub.col_offset)
        book = first_load if isinstance(sub.ctx, ast.Load) else first_store
        if sub.id not in book or key < book[sub.id]:
            book[sub.id] = key
    return {
        n for n, at in first_load.items()
        if n not in first_store or at <= first_store[n]
    }


@register
class AtomicSnapshotPass(Pass):
    id = "atomic-snapshot"
    version = "1"
    description = (
        "one logical operation split across two acquisitions of the same "
        "lock with state carried between the holds (check-then-act / "
        "torn snapshot — a concurrent writer between the holds makes the "
        "two halves disagree)"
    )

    def visit(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            regions = self._regions(ctx, node)
            if len(regions) < 2:
                continue
            yield from self._pair_up(ctx, node, regions)

    # ------------------------------------------------------ region scan
    def _regions(self, ctx: ModuleContext,
                 fn: ast.AST) -> list[_Region]:
        idx = self.index
        aliases = idx.aliases.get(ctx.module) if idx is not None else None
        out: list[_Region] = []
        for sub in walk_in_scope(fn):
            if isinstance(sub, (ast.With, ast.AsyncWith)):
                cls = enclosing_class(sub)
                for item in sub.items:
                    lid = lock_id(ctx, item.context_expr, cls, fn, aliases)
                    if lid is None:
                        continue
                    out.append(_Region(
                        lock=lid, node=sub, line=sub.lineno,
                        end_line=sub.end_lineno or sub.lineno,
                        defs=_names(sub, ast.Store),
                        uses=_live_uses(sub), kind="with"))
            elif isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name) \
                    and isinstance(sub.value, ast.Call):
                out.extend(self._call_region(
                    ctx, sub, sub.value, {sub.targets[0].id}))
            elif isinstance(sub, (ast.Expr, ast.Return)) \
                    and isinstance(sub.value, ast.Call):
                acq = self._acquire_region(ctx, fn, sub, aliases)
                if acq is not None:
                    out.append(acq)
                else:
                    out.extend(self._call_region(ctx, sub, sub.value, set()))
        return out

    def _acquire_region(self, ctx: ModuleContext, fn: ast.AST,
                        stmt: ast.stmt, aliases) -> _Region | None:
        """Region for the try/finally idiom: a bare statement-position
        ``X.acquire()`` (no args — a ``timeout=`` acquire is conditional,
        holding is not certain) whose NEXT SIBLING is a ``try:`` that
        releases the same lock in its ``finally:``. The region spans the
        ``try`` body — exactly what ``with X:`` would cover."""
        call = stmt.value  # type: ignore[attr-defined]
        if not (isinstance(stmt, ast.Expr)
                and isinstance(call.func, ast.Attribute)
                and call.func.attr == "acquire"
                and not call.args):
            return None
        cls = enclosing_class(stmt)
        lid = lock_id(ctx, call.func.value, cls, fn, aliases)
        if lid is None:
            return None
        nxt = _next_sibling(stmt)
        if not isinstance(nxt, ast.Try) or not nxt.finalbody:
            return None
        released = any(
            isinstance(fin, ast.Expr) and isinstance(fin.value, ast.Call)
            and isinstance(fin.value.func, ast.Attribute)
            and fin.value.func.attr == "release"
            and not fin.value.args
            and lock_id(ctx, fin.value.func.value, cls, fn, aliases) == lid
            for fin in nxt.finalbody
        )
        if not released:
            return None
        body = ast.Module(body=list(nxt.body), type_ignores=[])
        return _Region(lock=lid, node=nxt, line=stmt.lineno,
                       end_line=nxt.end_lineno or nxt.lineno,
                       defs=_names(body, ast.Store),
                       uses=_live_uses(body), kind="acquire")

    def _call_region(self, ctx: ModuleContext, stmt: ast.stmt,
                     call: ast.Call, defs: set) -> list[_Region]:
        """Regions for ``self.m(...)`` calls whose callee acquires locks
        (the hold happens on this method's behalf)."""
        idx = self.index
        if idx is None:
            return []
        f = call.func
        if not (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
                and f.value.id == "self"):
            return []
        q = idx.resolve_in(ctx.rel, call)
        if q is None:
            return []
        locks = idx.acquired_locks(q)
        if not locks:
            return []
        uses = _names(call, ast.Load) - {"self"}
        return [_Region(lock=lid, node=stmt, line=stmt.lineno,
                        end_line=stmt.end_lineno or stmt.lineno,
                        defs=set(defs), uses=uses, kind="call")
                for lid in sorted(locks)]

    # --------------------------------------------------------- pairing
    def _pair_up(self, ctx: ModuleContext, fn: ast.AST,
                 regions: list[_Region]) -> Iterator[Finding]:
        stores_by_name: dict[str, list[int]] = {}
        for sub in walk_in_scope(fn):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store):
                stores_by_name.setdefault(sub.id, []).append(sub.lineno)
        reported: set[tuple[int, int]] = set()
        for i, r1 in enumerate(regions):
            for r2 in regions:
                if r2 is r1 or r2.lock != r1.lock:
                    continue
                # strictly sequential, not nested (an ancestor's span
                # contains the descendant's)
                if not (r1.end_line < r2.line):
                    continue
                if self._is_ancestor(r1.node, r2.node) \
                        or self._is_ancestor(r2.node, r1.node):
                    continue
                # data flow into the second hold, or CONTROL flow: a
                # guard condition evaluated after the first hold that
                # decides whether the second hold runs (check-then-act)
                guard_uses = self._guard_names(fn, r2, r1.end_line)
                flow = {
                    n for n in (r1.defs & r2.uses)
                    if not any(r1.end_line < ln < r2.line
                               for ln in stores_by_name.get(n, ()))
                }
                # a guard whose name the second hold RE-DERIVES is
                # double-checked locking — the re-validation under the
                # second hold is exactly the fix, not the bug
                guard_flow = {
                    n for n in (r1.defs & guard_uses)
                    if not any(r1.end_line < ln < r2.line
                               for ln in stores_by_name.get(n, ()))
                } - flow - r2.defs
                if (not flow and not guard_flow) \
                        or (r1.line, r2.line) in reported:
                    continue
                reported.add((r1.line, r2.line))
                names = ", ".join(sorted(flow | guard_flow))
                how = ("is consumed under" if flow
                       else "gates whether this code runs under")
                yield Finding(
                    ctx.rel, r2.line, self.id,
                    f"'{names}' derived under a hold of {r1.lock} at "
                    f"line {r1.line} {how} a SECOND hold of "
                    "the same lock here — the two holds are not atomic; "
                    "a concurrent writer between them tears the snapshot "
                    "(take one copy under one hold, or merge/re-validate "
                    "under the second)",
                )

    @staticmethod
    def _guard_names(fn: ast.AST, r2: _Region, after_line: int) -> set:
        """Loaded names in the tests of If/While statements enclosing
        ``r2`` that are evaluated AFTER line ``after_line`` — the
        check-then-act guard path into the second hold."""
        out: set = set()
        cur = getattr(r2.node, "_dm_parent", None)
        while cur is not None and cur is not fn:
            if isinstance(cur, (ast.If, ast.While)) \
                    and cur.lineno > after_line:
                out |= _names(cur.test, ast.Load)
            cur = getattr(cur, "_dm_parent", None)
        return out

    @staticmethod
    def _is_ancestor(a: ast.AST, b: ast.AST) -> bool:
        cur = getattr(b, "_dm_parent", None)
        while cur is not None:
            if cur is a:
                return True
            cur = getattr(cur, "_dm_parent", None)
        return False
