"""no-bare-except: bare ``except:`` clauses, and broad handlers that
swallow silently.

A bare except catches ``KeyboardInterrupt``/``SystemExit`` and hides the
cancellation paths the delivery pipeline relies on. A broad
``except Exception:``/``except BaseException:`` whose body is only
``pass``/``continue`` erases the failure entirely — in a retry or
failover path that converts real corruption into silent degradation.
Handlers that log, re-raise, or record the error are fine.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.analyze.core import Finding, ModuleContext, Pass, dotted, register

_BROAD = {"Exception", "BaseException"}


def _caught_names(handler: ast.ExceptHandler) -> set[str]:
    t = handler.type
    nodes = t.elts if isinstance(t, ast.Tuple) else ([t] if t else [])
    out = set()
    for n in nodes:
        name = dotted(n)
        if name:
            out.add(name.split(".")[-1])
    return out


def _swallows(handler: ast.ExceptHandler) -> bool:
    for stmt in handler.body:
        if isinstance(stmt, ast.Pass) or isinstance(stmt, ast.Continue):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue  # docstring / ellipsis
        return False
    return True


@register
class BareExceptPass(Pass):
    id = "no-bare-except"
    description = (
        "bare `except:` and broad `except Exception: pass` handlers that "
        "silently swallow failures in retry/failover paths"
    )

    def visit(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                yield Finding(
                    ctx.rel, node.lineno, self.id,
                    "bare except catches KeyboardInterrupt/SystemExit and "
                    "hides cancellation — name the exception classes",
                )
                continue
            if _caught_names(node) & _BROAD and _swallows(node):
                yield Finding(
                    ctx.rel, node.lineno, self.id,
                    "broad handler swallows the failure with no log, "
                    "re-raise, or record — at minimum log it",
                )
