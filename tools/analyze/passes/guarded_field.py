"""guarded-field: RacerD-style lock-set race detection over ProjectIndex.

The tree is heavily multithreaded (reactor handoffs, bounded pools, the
tuner tick thread, swarm fill workers, telemetry samplers), and the bug
class that keeps surfacing in manual review is always the same shape: a
field written on a worker thread under one lock (or none) and read from
another thread under a different lock (or none). This pass proves the
absence of that shape compositionally, Infer/RacerD-style:

1. **Access summaries** — for every method of every class, each
   ``self.<attr>`` read/write site is recorded with the lock set held
   lexically at the site (``with self._lock:`` regions, identities
   normalized through :func:`tools.analyze.index.lock_id` plus per-class
   attribute aliasing, so ``self._mu = self._lock`` makes ``with
   self._mu:`` and ``with self._lock:`` the same lock).
2. **Caller-lock composition** — a lock the *caller* must hold at every
   resolved call site of a method protects the method's accesses too:
   the effective lock set at a site is its lexical set ∪ the
   INTERSECTION of locks held across all call sites of the enclosing
   method (must-hold, bounded depth through the call graph — the
   existing ``acquires-lock`` summaries feed the per-site held sets).
3. **Concurrency evidence** — a method is *worker-escaping* when any
   ``FunctionInfo.submit_calls`` edge anywhere in the run (``ex.submit``
   / ``Thread(target=…)`` / ``asyncio.to_thread``, any module) resolves
   to it, when it is an HTTP-handler-pool entry point (a ``do_*`` method
   of a ``BaseHTTPRequestHandler``-derived class — ThreadingHTTPServer
   runs one handler instance per live connection, so these entries are
   inherently multi-instance), or when it is call-graph-reachable from
   such a method. No evidence → no findings for the class
   (no-speculative-edges: a class nothing submits is not assumed
   concurrent). Handler entries carry an ownership exemption: each
   connection gets a FRESH handler instance confined to its pool thread,
   so accesses to the handler class's OWN fields do not race through its
   own entries — only the shared state its handlers call into (registry,
   store, the single-flight waiter map) does.
4. **Race check** — per field: a WRITE site and any other access site,
   at least one of them on a worker-escaping path, with DISJOINT
   effective lock sets, is a race finding; the blame names both sites
   and the submit edge that makes them concurrent.

Ownership filters (the RacerD "owned before shared" discipline):
``__init__`` accesses never participate and a field written ONLY in
``__init__`` is immutable-after-construction; lock-shaped attributes
and bound-method references are not data fields.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Iterator

from tools.analyze.core import (
    Finding,
    ModuleContext,
    Pass,
    dotted,
    enclosing_class,
    enclosing_function,
    register,
    walk_in_scope,
)
from tools.analyze.index import LOCKISH_RE, lock_id


#: receiver methods that mutate the container they are called on — a
#: ``self.ring.append(x)`` is a WRITE to the field's contents even though
#: the attribute node itself is a Load (dict/list mutation from two
#: threads is exactly the statusz attrs-dict bug class)
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "remove", "pop", "popleft",
    "popitem", "clear", "update", "setdefault", "add", "discard",
    "sort", "reverse",
})


#: constructors whose result is a known mutable container — only fields
#: bound to one of these ever count a ``.append()``-style call as a
#: write (``self.store.remove(key)`` on a domain object is that object's
#: API, and its internal locking is its own rule surface)
_CONTAINER_CTORS = {"dict", "list", "set", "deque", "defaultdict",
                    "OrderedDict", "Counter"}


#: stdlib request-handler bases whose subclasses the serving framework
#: instantiates ONCE PER CONNECTION on a pool thread — their ``do_*``
#: methods are thread entry points with no submit edge in sight
_HANDLER_BASE_RE = re.compile(
    r"(?:^|\.)(?:BaseHTTPRequestHandler|SimpleHTTPRequestHandler"
    r"|CGIHTTPRequestHandler|BaseRequestHandler|StreamRequestHandler"
    r"|DatagramRequestHandler)$")


def container_attrs(cls_node: ast.ClassDef) -> set[str]:
    """Attributes this class binds to a container literal/constructor in
    any of its methods (``self.ring = []``, ``self._peers = dict()``)."""
    out: set[str] = set()
    for sub in ast.walk(cls_node):
        if not (isinstance(sub, ast.Assign) and len(sub.targets) == 1
                and isinstance(sub.targets[0], ast.Attribute)
                and isinstance(sub.targets[0].value, ast.Name)
                and sub.targets[0].value.id == "self"):
            continue
        v = sub.value
        if isinstance(v, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                          ast.DictComp, ast.SetComp)):
            out.add(sub.targets[0].attr)
        elif isinstance(v, ast.Call):
            f = v.func
            name = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else None)
            if name in _CONTAINER_CTORS:
                out.add(sub.targets[0].attr)
    return out


def _is_write(sub: ast.Attribute, containers: set[str]) -> bool:
    """Store/Del/AugAssign target, subscript store (``self.d[k] = v``),
    or a mutating container method (``self.ring.append(x)``) on a field
    the class binds to a container."""
    if isinstance(sub.ctx, (ast.Store, ast.Del)):
        return True
    parent = getattr(sub, "_dm_parent", None)
    if isinstance(parent, ast.AugAssign):
        return True
    if isinstance(parent, ast.Subscript) and parent.value is sub \
            and isinstance(parent.ctx, (ast.Store, ast.Del)):
        return True
    if sub.attr in containers and isinstance(parent, ast.Attribute) \
            and parent.value is sub and parent.attr in _MUTATORS:
        grand = getattr(parent, "_dm_parent", None)
        if isinstance(grand, ast.Call) and grand.func is parent:
            return True
    return False


def _in_loop(node: ast.AST) -> bool:
    cur = getattr(node, "_dm_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
            return True
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return False
        cur = getattr(cur, "_dm_parent", None)
    return False


@dataclass(frozen=True)
class Access:
    cls: str            # owning class qname
    attr: str
    write: bool
    rel: str
    line: int
    locks: frozenset
    method: str         # enclosing method qname


@dataclass
class _MethodFacts:
    accesses: list = field(default_factory=list)     # [Access]
    #: resolved outgoing call sites: [(callee qname, lexical locks held)]
    calls: list = field(default_factory=list)


def _resolve_lock(ctx: ModuleContext, expr: ast.expr, where: ast.AST,
                  aliases: dict | None,
                  cls_lock_attrs: set[str] | None) -> str | None:
    """Lock identity of ``expr`` at ``where``: :func:`lock_id` first,
    then the per-class known-lock-attribute fallback (``self._cv`` bound
    to a Condition over a lock)."""
    cls = enclosing_class(where)
    efn = enclosing_function(where)
    lid = lock_id(ctx, expr, cls, efn, aliases)
    if lid is None and cls_lock_attrs \
            and isinstance(expr, ast.Attribute) \
            and isinstance(expr.value, ast.Name) \
            and expr.value.id == "self" \
            and expr.attr in cls_lock_attrs \
            and cls is not None:
        lid = f"{ctx.module}.{cls.name}.{expr.attr}"
    return lid


def acquire_regions(ctx: ModuleContext, fn: ast.AST,
                    aliases: dict | None,
                    cls_lock_attrs: set[str] | None = None
                    ) -> list[tuple[str, int, int]]:
    """``(lock id, acquire line, release line)`` intervals for bare
    ``X.acquire()`` … ``X.release()`` statement pairs inside ``fn`` —
    the try/finally idiom ``with`` can't express (e.g. conditional
    release, hold spanning a loop iteration boundary).

    Only STATEMENT-position, argument-free calls count: ``ok =
    lock.acquire(timeout=…)`` is a conditional acquire (holding is not
    certain), and ``budget.acquire(nbytes)`` is a different protocol
    entirely. Pairing is stack-like per lock id — each ``release()``
    closes the most recent unmatched ``acquire()`` of the same lock."""
    cached = getattr(fn, "_dm_acquire_regions", None)
    if cached is not None:
        return cached
    events: list[tuple[int, str, str]] = []
    for sub in walk_in_scope(fn):
        if not (isinstance(sub, ast.Expr) and isinstance(sub.value, ast.Call)):
            continue
        call = sub.value
        if not (isinstance(call.func, ast.Attribute)
                and call.func.attr in ("acquire", "release")
                and not call.args):
            continue
        lid = _resolve_lock(ctx, call.func.value, sub, aliases,
                            cls_lock_attrs)
        if lid is not None:
            events.append((sub.lineno, call.func.attr, lid))
    regions: list[tuple[str, int, int]] = []
    open_by_lock: dict[str, list[int]] = {}
    for line, kind, lid in sorted(events):
        if kind == "acquire":
            open_by_lock.setdefault(lid, []).append(line)
        else:
            stack = open_by_lock.get(lid)
            if stack:
                regions.append((lid, stack.pop(), line))
    fn._dm_acquire_regions = regions  # one module owns each fn node
    return regions


def _held_locks(node: ast.AST, ctx: ModuleContext, fn: ast.AST,
                aliases: dict | None,
                cls_lock_attrs: set[str] | None = None) -> set[str]:
    """Lock ids of every ``with``-statement enclosing ``node`` inside
    ``fn``, plus every bare ``acquire()``/``release()`` interval (the
    try/finally idiom) whose span covers the node. A node inside a
    ``withitem`` (the lock expression being acquired) does not count
    that With as held. ``cls_lock_attrs`` are extra ``self.<attr>``
    names known to BE locks for the enclosing class even when not
    lock-named — ``self._cv = threading.Condition(self._lock)`` makes
    ``with self._cv:`` hold the underlying lock."""
    held: set[str] = set()
    prev = node
    cur = getattr(node, "_dm_parent", None)
    while cur is not None and cur is not fn:
        if isinstance(cur, (ast.With, ast.AsyncWith)) \
                and not isinstance(prev, ast.withitem):
            for item in cur.items:
                lid = _resolve_lock(ctx, item.context_expr, cur, aliases,
                                    cls_lock_attrs)
                if lid is not None:
                    held.add(lid)
        prev, cur = cur, getattr(cur, "_dm_parent", None)
    for lid, start, end in acquire_regions(ctx, fn, aliases,
                                           cls_lock_attrs):
        # strictly after the acquire statement, up to the release line
        if start < node.lineno <= end:
            held.add(lid)
    return held


@register
class GuardedFieldPass(Pass):
    id = "guarded-field"
    version = "2"
    description = (
        "RacerD-style lock-set analysis: a field written on a "
        "worker-escaping path (ex.submit/Thread(target), or an HTTP "
        "handler-pool do_* entry point) and accessed elsewhere with a "
        "disjoint lock set is a data race — both sites and the "
        "submit/entry edge land in the blame"
    )

    #: caller-lock / reachability composition bound (matches the index's
    #: summary-depth discipline)
    MAX_DEPTH = 4

    def __init__(self) -> None:
        super().__init__()
        self._facts: dict[str, _MethodFacts] = {}      # method qname →
        self._lock_alias: dict[str, dict[str, str]] = {}  # class → a→b

    # ------------------------------------------------------------ visit
    def visit(self, ctx: ModuleContext) -> Iterator[Finding]:
        idx = self.index
        if idx is None:
            return iter(())
        aliases = idx.aliases.get(ctx.module)
        # per-class lock-attribute aliasing: ``self._mu = self._lock``
        # (direct alias) or ``self._cv = threading.Condition(self._lock)``
        # (a Condition ACQUIRES its underlying lock on __enter__) makes
        # the two names one lock identity — the aliased-attribute case
        # the lock-set intersection must see through. A Condition over
        # an ANONYMOUS lock (``threading.Condition()`` / ``Condition(
        # threading.Lock())``, the gen-engine idiom) has no second name
        # to alias to: the condition attribute IS the lock, whatever
        # it's called
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Attribute)
                    and isinstance(node.targets[0].value, ast.Name)
                    and node.targets[0].value.id == "self"):
                continue
            src_attr: str | None = None
            v = node.value
            if isinstance(v, ast.Attribute) and isinstance(v.value, ast.Name) \
                    and v.value.id == "self" and LOCKISH_RE.search(v.attr):
                src_attr = v.attr
            elif isinstance(v, ast.Call):
                fname = v.func.attr if isinstance(v.func, ast.Attribute) \
                    else (v.func.id if isinstance(v.func, ast.Name) else "")
                a0 = v.args[0] if v.args else None
                if fname == "Condition" and isinstance(a0, ast.Attribute) \
                        and isinstance(a0.value, ast.Name) \
                        and a0.value.id == "self" \
                        and LOCKISH_RE.search(a0.attr):
                    src_attr = a0.attr
                elif fname == "Condition" and (
                        a0 is None or (isinstance(a0, ast.Call) and
                                       (dotted(a0.func) or "").rsplit(
                                           ".", 1)[-1].endswith("Lock"))):
                    src_attr = node.targets[0].attr
            if src_attr is None:
                continue
            cls = enclosing_class(node)
            if cls is None:
                continue
            cq = idx._qname_of(ctx, cls)[0]
            self._lock_alias.setdefault(cq, {})[
                node.targets[0].attr] = src_attr

        containers: dict[str, set[str]] = {}
        for info in idx.functions.values():
            if info.rel != ctx.rel or info.cls is None:
                continue
            facts = self._facts.setdefault(info.qname, _MethodFacts())
            methods = idx.classes.get(info.cls, {})
            lock_attrs = set(self._lock_alias.get(info.cls, {}))
            if info.cls not in containers:
                cls_node = enclosing_class(info.node)
                containers[info.cls] = (container_attrs(cls_node)
                                        if cls_node is not None else set())
            for sub in walk_in_scope(info.node):
                if isinstance(sub, ast.Attribute) \
                        and isinstance(sub.value, ast.Name) \
                        and sub.value.id == "self":
                    attr = sub.attr
                    if attr in methods or LOCKISH_RE.search(attr) \
                            or attr in lock_attrs:
                        continue  # bound methods / sync objects
                    if info.name == "__init__":
                        continue  # owned before shared
                    write = _is_write(sub, containers[info.cls])
                    held = self._canon_locks(
                        _held_locks(sub, ctx, info.node, aliases,
                                    lock_attrs), info.cls)
                    facts.accesses.append(Access(
                        cls=info.cls, attr=attr, write=write, rel=ctx.rel,
                        line=sub.lineno, locks=frozenset(held),
                        method=info.qname))
                elif isinstance(sub, ast.Call):
                    q = idx.resolve_in(ctx.rel, sub)
                    if q is not None and q != info.qname:
                        held = self._canon_locks(
                            _held_locks(sub, ctx, info.node, aliases,
                                        lock_attrs),
                            info.cls)
                        facts.calls.append((q, frozenset(held)))
        return iter(())

    def _canon_locks(self, locks: set[str], cls: str | None) -> set[str]:
        """Rewrite this class's aliased lock attrs to their root name so
        intersecting-through-an-alias lock sets actually intersect."""
        alias = self._lock_alias.get(cls or "", None)
        if not alias:
            return locks
        out = set()
        for lid in locks:
            head, _, attr = lid.rpartition(".")
            seen = set()
            while attr in alias and attr not in seen:
                seen.add(attr)
                attr = alias[attr]
            out.add(f"{head}.{attr}" if head else attr)
        return out

    # --------------------------------------------------------- finalize
    def finalize(self) -> Iterator[Finding]:
        idx = self.index
        if idx is None:
            return
        # late alias canonicalization: visit order is arbitrary, so an
        # alias collected AFTER a module's accesses must still apply
        for q, facts in self._facts.items():
            info = idx.functions.get(q)
            cls = info.cls if info else None
            facts.accesses = [
                Access(a.cls, a.attr, a.write, a.rel, a.line,
                       frozenset(self._canon_locks(set(a.locks), a.cls)),
                       a.method)
                for a in facts.accesses]
            facts.calls = [(c, frozenset(self._canon_locks(set(h), cls)))
                           for c, h in facts.calls]

        # concurrency evidence: methods any submit edge resolves to
        # (entries), closed over the call graph (bounded). Each entry
        # remembers its submit site and whether MULTIPLE instances of
        # that worker can exist (submitted inside a loop, or from two
        # distinct sites) — two accesses reachable only from one
        # single-instance entry run on ONE thread and never race.
        entries: dict[str, list] = {}  # entry → [rel, line, multi]
        for info in idx.functions.values():
            for q, _raw, node in info.submit_calls:
                if q not in idx.functions:
                    continue
                multi = _in_loop(node)
                prev = entries.get(q)
                if prev is None:
                    entries[q] = [info.rel, node.lineno, multi]
                else:
                    prev[2] = True  # second submit site → multi-instance
        # HTTP-handler-pool roots: every do_* method of a request-handler
        # subclass is an entry the serving framework calls on a pool
        # thread, one FRESH instance per live connection — inherently
        # multi-instance. ``confined`` records the owning handler class:
        # the instance itself is thread-confined, so the handler's OWN
        # fields are exempt from racing through these entries (ownership)
        # while everything the handler calls into keeps the root.
        confined: dict[str, str] = {}
        for cq in idx.classes:
            if not self._is_handler_class(cq):
                continue
            for mname, mq in idx.classes.get(cq, {}).items():
                if not mname.startswith("do_"):
                    continue
                m_info = idx.functions.get(mq)
                if m_info is None:
                    continue
                prev = entries.get(mq)
                if prev is None:
                    entries[mq] = [m_info.rel, m_info.node.lineno, True]
                else:
                    prev[2] = True
                confined[mq] = cq
        #: method qname → set of entry qnames it can run under
        roots: dict[str, set[str]] = {q: {q} for q in entries}
        frontier = list(entries)
        for _ in range(self.MAX_DEPTH):
            nxt = []
            for q in frontier:
                for callee, _h in self._facts.get(q, _MethodFacts()).calls:
                    tgt = roots.setdefault(callee, set())
                    before = len(tgt)
                    tgt |= roots[q]
                    if len(tgt) != before:
                        nxt.append(callee)
            frontier = nxt
        worker_set = {q for q, r in roots.items() if r}
        # main-capability: a method OUTSIDE the worker closure runs on
        # the spawning side; a method inside it is also main-capable
        # when some caller outside the closure reaches it
        main_capable: set[str] = set()
        for q in self._facts:
            if q not in worker_set:
                main_capable.add(q)
        for q, facts in self._facts.items():
            if q in main_capable:
                for callee, _h in facts.calls:
                    if callee in worker_set:
                        main_capable.add(callee)

        # caller-lock must-hold sets (intersection over all call sites)
        callers: dict[str, list] = {}
        for q, facts in self._facts.items():
            for callee, held in facts.calls:
                callers.setdefault(callee, []).append((q, held))
        memo: dict[str, frozenset] = {}

        def must_hold(q: str, depth: int) -> frozenset:
            if q in memo:
                return memo[q]
            memo[q] = frozenset()  # cycle guard: assume nothing held
            sites = callers.get(q)
            out: frozenset | None = None
            if sites and depth > 0:
                for caller_q, held in sites:
                    eff = held | must_hold(caller_q, depth - 1)
                    out = eff if out is None else (out & eff)
            memo[q] = out or frozenset()
            return memo[q]

        # group effective access sites per (class, field)
        fields: dict[tuple[str, str], list[Access]] = {}
        for q, facts in self._facts.items():
            extra = must_hold(q, self.MAX_DEPTH)
            for a in facts.accesses:
                eff = a if not extra else Access(
                    a.cls, a.attr, a.write, a.rel, a.line,
                    a.locks | extra, a.method)
                fields.setdefault((a.cls, a.attr), []).append(eff)

        reported: set[tuple[str, str]] = set()
        for (cls, attr), sites in sorted(fields.items()):
            writes = [s for s in sites if s.write]
            if not writes:
                continue  # immutable after __init__ (init sites excluded)
            pair = self._racing_pair(writes, sites, roots, main_capable,
                                     entries, confined)
            if pair is None or (cls, attr) in reported:
                continue
            reported.add((cls, attr))
            w, other, (sub_rel, sub_line) = pair
            wl = self._fmt(w.locks)
            ol = self._fmt(other.locks)
            kind = "written" if other.write else "read"
            yield Finding(
                w.rel, w.line, self.id,
                f"field '{attr}' of {cls} written here under {wl} and "
                f"{kind} at {other.rel}:{other.line} under {ol} — lock "
                "sets are disjoint and the method escapes to a worker "
                f"(submitted at {sub_rel}:{sub_line}); a concurrent "
                "interleaving tears this field",
            )

    def _racing_pair(self, writes, sites, roots, main_capable, entries,
                     confined):
        """First (write, other-access, submit-site) with disjoint locks
        that can execute on two DIFFERENT threads: distinct worker
        entries, worker vs main, or one multi-instance worker entry.
        A handler entry confined to the access's own class is dropped
        from that access's root set (per-connection handler instances
        are thread-confined — their own fields never race through their
        own entries)."""
        for w in sorted(writes, key=lambda s: (s.rel, s.line)):
            wr = {e for e in roots.get(w.method, set())
                  if confined.get(e) != w.cls}
            wm = w.method in main_capable
            for a in sorted(sites, key=lambda s: (s.rel, s.line)):
                same_site = (a.rel, a.line) == (w.rel, w.line)
                ar = {e for e in roots.get(a.method, set())
                      if confined.get(e) != a.cls}
                am = a.method in main_capable
                if not wr and not ar:
                    continue  # no worker evidence on either side
                if same_site:
                    # one site racing ITSELF needs two live instances of
                    # its worker (submitted in a loop / from two sites)
                    multi = [e for e in sorted(wr)
                             if entries[e][2]]
                    if not multi:
                        continue
                    evidence = tuple(entries[multi[0]][:2])
                else:
                    evidence = self._concurrent(wr, wm, ar, am, entries)
                if evidence is None:
                    continue
                if w.locks & a.locks:
                    continue
                return w, a, evidence
        return None

    def _is_handler_class(self, cq: str) -> bool:
        """Does ``cq`` derive (transitively through project classes) from
        a stdlib request-handler base?"""
        idx = self.index
        memo: dict[str, bool] = {}

        def walk(q: str) -> bool:
            if q in memo:
                return memo[q]
            memo[q] = False  # cycle guard
            out = any(_HANDLER_BASE_RE.search(b) or walk(b)
                      for b in idx.class_bases.get(q, ()))
            memo[q] = out
            return out

        return walk(cq)

    @staticmethod
    def _concurrent(wr, wm, ar, am, entries):
        """Submit-site evidence that the two sides can overlap, or None.
        Distinct roots overlap; one root overlaps itself only when its
        entry is multi-instance; main overlaps any worker root."""
        for e in sorted(wr):
            rel, line, multi = entries[e]
            if am or (ar - {e}) or (e in ar and multi):
                return rel, line
        for e in sorted(ar):
            rel, line, multi = entries[e]
            if wm or (wr - {e}) or (e in wr and multi):
                return rel, line
        return None

    @staticmethod
    def _fmt(locks: frozenset) -> str:
        if not locks:
            return "NO lock"
        return "{" + ", ".join(sorted(locks)) + "}"
