"""hbm-budget: device allocations on the delivery/sink planes must be
accounted — placed through the sharding plan or charged to a ByteBudget.

The sink's whole contract is that HBM and host-RAM residency are known
quantities: every tensor lands under a ``ShardingPlan``-derived
``NamedSharding`` (``sink/hbm.py``'s ``place_tensor`` family) and every
landing buffer is charged to the delivery ``ByteBudget`` before the
bytes exist (``sink/streaming.py``). An allocation that bypasses both is
invisible to that accounting: a bare ``jax.device_put(x)`` lands the
whole tensor replicated on the default device, and an uncharged landing
buffer on a concurrent fetch path can pin ``workers × shard`` host RAM.

Three finding classes, on sink-plane modules (``demodel_tpu/sink/``,
``demodel_tpu/delivery.py``, or a ``# demodel: sink-plane`` pragma):

1. ``jax.device_put``/``jax.make_array_from_single_device_arrays`` whose
   placement argument is missing or not *plan-derived*. Plan-derived:
   the result of ``.sharding_for(...)`` or ``NamedSharding(...)``, or
   anything reached from one (``sharding.addressable_devices_indices_map``
   → ``dev_map`` → ``for device, idx in dev_map.items():``). A placement
   fed by a function PARAMETER is judged through the call graph: the
   allocation is fine when some resolved caller demonstrably threads a
   plan-derived value through it (the contract is proven — how
   ``place_tensor``'s ``device=`` stays accounted from two modules away),
   and the blame moves to call sites — a sink-plane call that fills such
   a placement parameter with a value NOT derived from the plan is the
   finding (Infer-style: report where the contract breaks, not where the
   primitive lives). Callers outside the sink plane (e.g. the restore
   plane, a consumer with its own exact layout) are not judged.
2. ``jnp.*`` array constructors — the sink plane moves bytes, it does
   not make tensors; a ``jnp.zeros`` here is an unplanned replicated
   allocation.
3. a host landing buffer (``np.empty``/``np.zeros``/``bytearray``)
   allocated inside a function that ESCAPES to a worker
   (``executor.submit(f)`` / ``Thread(target=f)``) and is filled by a
   ``pread_into``-style ranged read, with no ``<budget>.acquire(...)``
   in the function or its enclosing scope — concurrent landing buffers
   outside the ByteBudget are exactly the unbounded-RAM failure mode the
   budget exists to prevent.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.analyze.core import (
    Finding,
    ModuleContext,
    Pass,
    dotted,
    enclosing_function,
    register,
    walk_in_scope,
)
from tools.analyze.index import JNP_ALLOCATORS

SINK_PRAGMA = "# demodel: sink-plane"
_SINK_PATHS = ("demodel_tpu/sink/",)
_SINK_FILES = ("demodel_tpu/delivery.py",)

_PLACED_ALLOCATORS = {"jax.device_put",
                      "jax.make_array_from_single_device_arrays"}
#: argument position of the placement (device/sharding) operand
_PLACEMENT_POS = {"jax.device_put": 1,
                  "jax.make_array_from_single_device_arrays": 1}
_PLACEMENT_KW = {"jax.device_put": ("device",),
                 "jax.make_array_from_single_device_arrays": ("sharding",)}

_HOST_BUFFER_CTORS = {"np.empty", "np.zeros", "numpy.empty", "numpy.zeros",
                      "bytearray"}
_RANGED_READS = {"pread_into", "read_into", "readinto"}

#: callers examined per parameter while composing placement summaries
_MAX_DEPTH = 3


def _is_sink_plane(ctx: ModuleContext) -> bool:
    return (
        any(ctx.rel.startswith(p) for p in _SINK_PATHS)
        or ctx.rel in _SINK_FILES
        or SINK_PRAGMA in ctx.source
    )


def _plan_derived_names(fn: ast.AST, seed: frozenset = frozenset()) -> set[str]:
    """Names in ``fn``'s scope that hold plan/sharding-derived values:
    seeded by ``.sharding_for(...)`` / ``NamedSharding(...)`` results
    (plus ``seed`` — used to test whether a parameter feeds a placement),
    closed over attribute/method derivation, aliasing, and tuple loop
    targets over a derived mapping."""
    derived: set[str] = set(seed)

    def value_derived(expr: ast.AST) -> bool:
        if isinstance(expr, ast.Call):
            name = dotted(expr.func) or ""
            if name.endswith(".sharding_for") or name == "NamedSharding" \
                    or name.endswith(".NamedSharding"):
                return True
            # method on a derived receiver: sharding.addressable_...()
            if isinstance(expr.func, ast.Attribute) \
                    and isinstance(expr.func.value, ast.Name) \
                    and expr.func.value.id in derived:
                return True
        if isinstance(expr, ast.Name):
            return expr.id in derived
        if isinstance(expr, ast.Attribute):
            return isinstance(expr.value, ast.Name) \
                and expr.value.id in derived
        return False

    # fixed point: derivation chains (sharding → dev_map → device) can
    # appear in any statement order
    for _ in range(4):
        before = len(derived)
        for node in walk_in_scope(fn):
            if isinstance(node, ast.Assign) and value_derived(node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        derived.add(tgt.id)
            elif isinstance(node, ast.For) and value_derived(node.iter):
                for tgt in ast.walk(node.target):
                    if isinstance(tgt, ast.Name):
                        derived.add(tgt.id)
            elif isinstance(node, ast.comprehension) \
                    and value_derived(node.iter):
                for tgt in ast.walk(node.target):
                    if isinstance(tgt, ast.Name):
                        derived.add(tgt.id)
        if len(derived) == before:
            break
    return derived


def _root_name(expr: ast.AST) -> str | None:
    root = expr
    while isinstance(root, (ast.Attribute, ast.Subscript)):
        root = root.value
    return root.id if isinstance(root, ast.Name) else None


def _placement_expr(call: ast.Call, name: str) -> ast.AST | None:
    pos = _PLACEMENT_POS[name]
    if len(call.args) > pos and not any(
            isinstance(a, ast.Starred) for a in call.args[:pos + 1]):
        return call.args[pos]
    for kw in call.keywords:
        if kw.arg in _PLACEMENT_KW[name]:
            return kw.value
    return None


@register
class HbmBudgetPass(Pass):
    id = "hbm-budget"
    description = (
        "device allocation on the delivery/sink plane that bypasses the "
        "sharding plan and the ByteBudget (unplanned HBM / unbounded "
        "landing RAM)"
    )

    def __init__(self) -> None:
        super().__init__()
        #: sink-plane contexts seen (call-site contract checks run in
        #: finalize, once the param-placed allocator set is complete)
        self._sink_ctxs: list = []
        #: allocator qname → placement param name (functions whose device
        #: allocation is placed through a parameter)
        self._param_placed: dict[str, str] = {}

    # ---------------------------------------------------------- helpers
    def _fn_budgeted(self, fn: ast.AST | None) -> bool:
        """Does ``fn`` (or an enclosing def) charge a ByteBudget?"""
        while fn is not None:
            info = self._info_for(fn)
            if info is not None and info.budget_acquire:
                return True
            fn = enclosing_function(fn)
        return False

    def _locally_accounted(self, fn: ast.AST, expr: ast.AST) -> bool:
        """Plan-derived within ``fn``'s own scope (no caller knowledge)."""
        if isinstance(expr, ast.Call):
            name = dotted(expr.func) or ""
            if name.endswith(".sharding_for") or name == "NamedSharding" \
                    or name.endswith(".NamedSharding"):
                return True
        root = _root_name(expr)
        return root is not None and root in _plan_derived_names(fn)

    def _placement_param(self, fn: ast.AST, expr: ast.AST) -> str | None:
        """The parameter of ``fn`` that feeds this placement expr
        (possibly through locals: sharding → dev_map → device)."""
        root = _root_name(expr)
        info = self._info_for(fn)
        if root is None or info is None:
            return None
        for p in info.params:
            if p != "self" and root in _plan_derived_names(
                    fn, frozenset({p})):
                return p
        return None

    def _info_for(self, fn: ast.AST):
        if self.index is None:
            return None
        return self.index.by_node.get(id(fn))

    def _arg_for(self, info, call: ast.Call, param: str) -> ast.AST | None:
        try:
            pos = info.params.index(param)
        except ValueError:
            return None
        if info.cls is not None and info.params and info.params[0] == "self":
            pos -= 1  # call sites don't pass self
        if len(call.args) > pos and not any(
                isinstance(a, ast.Starred) for a in call.args[:pos + 1]):
            return call.args[pos]
        for kw in call.keywords:
            if kw.arg == param:
                return kw.value
        return None

    def _site_accounted(self, fn: ast.AST, expr: ast.AST,
                        depth: int) -> bool:
        """Accounted at this site: locally plan-derived, or fed by a
        parameter that SOME resolved caller fills with an accounted value
        (bounded composition — proves the plan is threaded through)."""
        if self._locally_accounted(fn, expr):
            return True
        param = self._placement_param(fn, expr)
        info = self._info_for(fn)
        if param is None or info is None or depth <= 0:
            return False
        for caller, call in self.index.callers_of(info.qname):
            arg = self._arg_for(info, call, param)
            if arg is not None and self._site_accounted(
                    caller.node, arg, depth - 1):
                return True
        return False

    # ------------------------------------------------------------ visit
    def visit(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not _is_sink_plane(ctx):
            return
        self._sink_ctxs.append(ctx)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            if name in _PLACED_ALLOCATORS:
                fn = enclosing_function(node) or ctx.tree
                if self._fn_budgeted(enclosing_function(node)):
                    continue
                expr = _placement_expr(node, name)
                if expr is None:
                    yield Finding(
                        ctx.rel, node.lineno, self.id,
                        f"{name}(...) with no device/sharding operand lands "
                        "the whole tensor replicated on the default device, "
                        "outside the sharding plan",
                    )
                    continue
                param = self._placement_param(fn, expr) \
                    if not self._locally_accounted(fn, expr) else None
                if param is not None:
                    info = self._info_for(fn)
                    if info is not None:
                        # call sites are judged in finalize; the
                        # allocation itself is fine once some caller
                        # proves the plan threads through
                        self._param_placed.setdefault(info.qname, param)
                if not self._site_accounted(fn, expr, _MAX_DEPTH):
                    yield Finding(
                        ctx.rel, node.lineno, self.id,
                        f"{name}(...) placement is not derived from the "
                        "sharding plan (plan.sharding_for / NamedSharding) "
                        "— these device bytes bypass delivery accounting",
                    )
            elif name in JNP_ALLOCATORS:
                if self._fn_budgeted(enclosing_function(node)):
                    continue
                yield Finding(
                    ctx.rel, node.lineno, self.id,
                    f"{name}(...) materializes an unplanned device array on "
                    "the sink plane — route tensors through the plan "
                    "(place_tensor) or move this off the delivery path",
                )
        yield from self._check_worker_buffers(ctx)

    def finalize(self) -> Iterator[Finding]:
        """Call-site contract checks: a sink-plane call that fills a
        param-placed allocator's placement parameter with a value not
        derived from the plan is where the accounting breaks."""
        if self.index is None or not self._param_placed:
            return
        for ctx in self._sink_ctxs:
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                q = self.index.resolve_in(ctx.rel, node)
                if q is None or q not in self._param_placed:
                    continue
                callee = self.index.functions[q]
                param = self._param_placed[q]
                owner = self.index.owner_of(ctx.rel, node)
                fn = owner.node if owner is not None else ctx.tree
                if self._fn_budgeted(owner.node if owner else None):
                    continue
                arg = self._arg_for(callee, node, param)
                if arg is None:
                    continue
                if not self._site_accounted(fn, arg, _MAX_DEPTH):
                    yield Finding(
                        ctx.rel, node.lineno, self.id,
                        f"{q.rsplit('.', 1)[-1]}() places device bytes "
                        f"through its {param!r} parameter, but this call "
                        "fills it with a value not derived from the "
                        "sharding plan (plan.sharding_for / NamedSharding)",
                    )

    def _check_worker_buffers(self, ctx: ModuleContext) -> Iterator[Finding]:
        if self.index is None:
            return
        escaped: set[str] = set()
        for info in self.index.functions.values():
            if info.rel == ctx.rel:
                escaped |= info.escapes_to_worker
        if not escaped:
            return
        for info in self.index.functions.values():
            if info.rel != ctx.rel or info.name not in escaped:
                continue
            if self._fn_budgeted(info.node):
                continue
            # two sweeps: walk_in_scope order is not source order, so
            # collect the buffer names first, then look for ranged reads
            buffers: dict[str, int] = {}
            for sub in walk_in_scope(info.node):
                if isinstance(sub, ast.Assign) \
                        and isinstance(sub.value, ast.Call) \
                        and (dotted(sub.value.func) or "") \
                        in _HOST_BUFFER_CTORS:
                    for tgt in sub.targets:
                        if isinstance(tgt, ast.Name):
                            buffers[tgt.id] = sub.value.lineno
            fed = False
            for sub in walk_in_scope(info.node):
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr in _RANGED_READS:
                    for arg in list(sub.args) + [k.value for k in
                                                 sub.keywords]:
                        root = arg
                        while isinstance(root, (ast.Attribute,
                                                ast.Subscript, ast.Call)):
                            root = getattr(root, "value",
                                           getattr(root, "func", None))
                            if root is None:
                                break
                        if isinstance(root, ast.Name) and root.id in buffers:
                            fed = True
            if buffers and fed:
                line = min(buffers.values())
                yield Finding(
                    ctx.rel, line, self.id,
                    f"landing buffer in {info.name}() runs on a worker "
                    "(submitted to an executor/thread) without "
                    "ByteBudget.acquire — concurrent fetch buffers outside "
                    "the budget can pin workers × shard bytes of host RAM",
                )
