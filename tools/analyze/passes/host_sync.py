"""no-host-sync-in-hot-path: flag device→host synchronization on the
delivery hot path (demodel_tpu/{ops,sink,parallel}).

``.block_until_ready()``, plus ``np.asarray``/``np.array``/``float``/
``int``/``bool``/``.item()``/``.tolist()`` applied to values produced by
``jnp.*``/``jax.*`` calls in the same function. Each of these forces the
host to wait on the device stream — inside the streamed-delivery window
that serializes fetch, dispatch, and transfer and silently caps
throughput.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.analyze.core import (
    Finding,
    ModuleContext,
    Pass,
    dotted,
    register,
    walk_in_scope,
)

#: jax.* calls that return HOST values (device handles, counts, pytree
#: plumbing) — their results are not device arrays, so consuming them on
#: the host is not a sync
_HOST_RESULT = {
    "jax.devices", "jax.local_devices", "jax.device_count",
    "jax.local_device_count", "jax.process_count", "jax.process_index",
    "jax.default_backend", "jax.make_mesh", "jax.random.split",
}
_HOST_RESULT_PREFIXES = ("jax.tree", "jax.sharding", "jax.dtypes")

_CONVERTERS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
               "float", "int", "bool"}
_SYNC_METHODS = {"item", "tolist"}


def _device_producer(call: ast.Call) -> bool:
    name = dotted(call.func)
    if not name:
        return False
    if name in _HOST_RESULT or name.startswith(_HOST_RESULT_PREFIXES):
        return False
    return name.startswith(("jnp.", "jax."))


def _tainted_names(fn: ast.AST) -> set[str]:
    """Names assigned from a jnp./jax. call in ``fn``'s own scope (nested
    defs are separate scopes analyzed on their own — a closure's device
    locals must not taint same-named host values outside it)."""
    out: set[str] = set()
    for node in walk_in_scope(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call) \
                and _device_producer(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out.add(tgt.id)
    return out


@register
class HostSyncPass(Pass):
    id = "no-host-sync-in-hot-path"
    description = (
        "device→host sync (.block_until_ready / np.asarray / float / .item "
        "on device values) inside demodel_tpu/{ops,sink,parallel}"
    )

    def visit(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not ctx.hot:
            return
        scopes = [ctx.tree] + [
            n for n in ast.walk(ctx.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        seen: set[int] = set()
        for scope in scopes:
            tainted = _tainted_names(scope) if scope is not ctx.tree else set()
            for node in walk_in_scope(scope):
                if not isinstance(node, ast.Call) or id(node) in seen:
                    continue
                f = self._check_call(ctx, node, tainted)
                if f is not None:
                    seen.add(id(node))
                    yield f

    def _check_call(self, ctx: ModuleContext, node: ast.Call,
                    tainted: set[str]) -> Finding | None:
        name = dotted(node.func)
        # hard sync, whatever the receiver
        if name == "jax.block_until_ready" or (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "block_until_ready"
        ):
            return Finding(
                ctx.rel, node.lineno, self.id,
                "block_until_ready forces a full device sync on the hot "
                "path — move it off the delivery critical path",
            )
        # .item()/.tolist() on a device-tainted name
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in _SYNC_METHODS
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in tainted):
            return Finding(
                ctx.rel, node.lineno, self.id,
                f".{node.func.attr}() on device value "
                f"{node.func.value.id!r} copies to host and blocks on the "
                "device stream",
            )
        # host converters applied to a device value
        if name in _CONVERTERS and node.args:
            arg = node.args[0]
            arg_is_device = (
                (isinstance(arg, ast.Name) and arg.id in tainted)
                or (isinstance(arg, ast.Call) and _device_producer(arg))
            )
            if arg_is_device:
                return Finding(
                    ctx.rel, node.lineno, self.id,
                    f"{name}(...) on a device value materializes it on host "
                    "(hidden device sync + copy)",
                )
        return None
