"""no-host-sync-in-hot-path: flag device→host synchronization on the
delivery hot path (demodel_tpu/{ops,sink,parallel}).

``.block_until_ready()``, plus ``np.asarray``/``np.array``/``float``/
``int``/``bool``/``.item()``/``.tolist()`` applied to device values. Each
of these forces the host to wait on the device stream — inside the
streamed-delivery window that serializes fetch, dispatch, and transfer
and silently caps throughput.

Device values are tracked **interprocedurally** through the
ProjectIndex: a name assigned from a call whose resolved callee
(bounded-depth summary composition, any module) returns a device value is
tainted the same as a direct ``jnp.*``/``jax.*`` producer — so a tensor
built in ``ops/`` and synced in ``sink/`` is visible even though neither
module alone shows both halves.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.analyze.core import (
    Finding,
    ModuleContext,
    Pass,
    dotted,
    register,
    walk_in_scope,
)
from tools.analyze.index import device_producer

_CONVERTERS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
               "float", "int", "bool"}
_SYNC_METHODS = {"item", "tolist"}


@register
class HostSyncPass(Pass):
    id = "no-host-sync-in-hot-path"
    description = (
        "device→host sync (.block_until_ready / np.asarray / float / .item "
        "on device values, incl. values returned across module boundaries) "
        "inside demodel_tpu/{ops,sink,parallel}"
    )

    def _device_call(self, ctx: ModuleContext, call: ast.Call) -> bool:
        """Direct jnp./jax. producer, or a resolved project callee whose
        bounded summary says it returns a device value."""
        if device_producer(call):
            return True
        if self.index is not None:
            q = self.index.resolve_in(ctx.rel, call)
            if q is not None and self.index.returns_device(q):
                return True
        return False

    def _tainted_names(self, ctx: ModuleContext, fn: ast.AST) -> set[str]:
        """Names assigned from a device-producing call in ``fn``'s own
        scope (nested defs are separate scopes analyzed on their own — a
        closure's device locals must not taint same-named host values
        outside it)."""
        out: set[str] = set()
        for node in walk_in_scope(fn):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call) \
                    and self._device_call(ctx, node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out.add(tgt.id)
        return out

    def visit(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not ctx.hot:
            return
        scopes = [ctx.tree] + [
            n for n in ast.walk(ctx.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        seen: set[int] = set()
        for scope in scopes:
            tainted = self._tainted_names(ctx, scope) \
                if scope is not ctx.tree else set()
            for node in walk_in_scope(scope):
                if not isinstance(node, ast.Call) or id(node) in seen:
                    continue
                f = self._check_call(ctx, node, tainted)
                if f is not None:
                    seen.add(id(node))
                    yield f

    def _check_call(self, ctx: ModuleContext, node: ast.Call,
                    tainted: set[str]) -> Finding | None:
        name = dotted(node.func)
        # hard sync, whatever the receiver
        if name == "jax.block_until_ready" or (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "block_until_ready"
        ):
            return Finding(
                ctx.rel, node.lineno, self.id,
                "block_until_ready forces a full device sync on the hot "
                "path — move it off the delivery critical path",
            )
        # .item()/.tolist() on a device-tainted name
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in _SYNC_METHODS
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in tainted):
            return Finding(
                ctx.rel, node.lineno, self.id,
                f".{node.func.attr}() on device value "
                f"{node.func.value.id!r} copies to host and blocks on the "
                "device stream",
            )
        # host converters applied to a device value (assigned locally, OR
        # returned straight out of a resolved cross-module callee)
        if name in _CONVERTERS and node.args:
            arg = node.args[0]
            arg_is_device = (
                (isinstance(arg, ast.Name) and arg.id in tainted)
                or (isinstance(arg, ast.Call) and self._device_call(ctx, arg))
            )
            if arg_is_device:
                why = ""
                if isinstance(arg, ast.Call) and not device_producer(arg):
                    q = self.index.resolve_in(ctx.rel, arg) \
                        if self.index else None
                    if q is not None:
                        why = f" (device value returned by {q})"
                return Finding(
                    ctx.rel, node.lineno, self.id,
                    f"{name}(...) on a device value materializes it on host "
                    f"(hidden device sync + copy){why}",
                )
        return None
