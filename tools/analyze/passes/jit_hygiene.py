"""jit-hygiene: Python-level control flow on traced arguments inside
``@jax.jit`` functions, and non-hashable static-arg declarations.

``if``/``while`` on a traced value raises ``TracerBoolConversionError``
at trace time at best; at worst (when the branch happens to be constant
under the first trace) it silently bakes one branch into the compiled
program. ``x is None`` / ``x is not None`` tests and ``isinstance``
checks are structural (the argument is Python-level there) and are
allowed. ``static_argnums``/``static_argnames`` passed as a list/set/
dict display is unhashable-by-convention — jit accepts some of these at
Python level but the cache key contract wants tuples.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.analyze.core import Finding, ModuleContext, Pass, dotted, register

_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}


def _jit_call(node: ast.AST) -> ast.Call | None:
    """The ``jax.jit(...)`` call inside a decorator/callsite expression,
    unwrapping ``functools.partial(jax.jit, ...)``."""
    if isinstance(node, ast.Call):
        name = dotted(node.func)
        if name in _JIT_NAMES:
            return node
        if name in ("functools.partial", "partial") and node.args:
            inner = dotted(node.args[0])
            if inner in _JIT_NAMES:
                return node
    elif dotted(node) in _JIT_NAMES:
        # bare @jax.jit decorator — no kwargs
        return None
    return None


def _is_jit_decorator(node: ast.AST) -> bool:
    if dotted(node) in _JIT_NAMES:
        return True
    return _jit_call(node) is not None


def _static_names(call: ast.Call | None, fn: ast.FunctionDef) -> set[str]:
    """Param names declared static via static_argnums/static_argnames."""
    if call is None:
        return set()
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    out: set[str] = set()
    for kw in call.keywords:
        val = kw.value
        items = val.elts if isinstance(val, (ast.Tuple, ast.List, ast.Set)) \
            else [val]
        if kw.arg == "static_argnames":
            out |= {i.value for i in items
                    if isinstance(i, ast.Constant) and isinstance(i.value, str)}
        elif kw.arg == "static_argnums":
            for i in items:
                if isinstance(i, ast.Constant) and isinstance(i.value, int) \
                        and i.value < len(params):
                    out.add(params[i.value])
    return out


def _branch_hazards(fn: ast.FunctionDef, traced: set[str]):
    """(node, name) for if/while tests referencing a traced param."""
    for node in ast.walk(fn):
        if not isinstance(node, (ast.If, ast.While)):
            continue
        test = node.test
        # structural tests are fine: `x is (not) None`, isinstance(x, T)
        if isinstance(test, ast.Compare) and any(
            isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops
        ):
            continue
        if isinstance(test, ast.Call) and dotted(test.func) == "isinstance":
            continue
        # names only referenced inside isinstance(...) are structural
        structural: set[int] = set()
        for sub in ast.walk(test):
            if isinstance(sub, ast.Call) and dotted(sub.func) == "isinstance":
                structural.update(id(n) for n in ast.walk(sub))
        for sub in ast.walk(test):
            if (isinstance(sub, ast.Name) and sub.id in traced
                    and id(sub) not in structural):
                yield node, sub.id
                break


@register
class JitHygienePass(Pass):
    id = "jit-hygiene"
    description = (
        "Python if/while on traced args inside @jax.jit functions; "
        "list/set/dict static_argnums declarations (unhashable cache keys)"
    )

    def visit(self, ctx: ModuleContext) -> Iterator[Finding]:
        # non-hashable static declarations at ANY jit call site
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and dotted(node.func) in _JIT_NAMES:
                for kw in node.keywords:
                    if kw.arg in ("static_argnums", "static_argnames") \
                            and isinstance(kw.value,
                                           (ast.List, ast.Set, ast.Dict)):
                        yield Finding(
                            ctx.rel, node.lineno, self.id,
                            f"{kw.arg} given a "
                            f"{type(kw.value).__name__.lower()} display — "
                            "use a hashable tuple",
                        )
        # traced-arg branching in decorated functions
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, ast.FunctionDef):
                continue
            jit_deco = None
            for deco in fn.decorator_list:
                if _is_jit_decorator(deco):
                    jit_deco = deco
                    break
            if jit_deco is None:
                continue
            static = _static_names(_jit_call(jit_deco), fn)
            params = {a.arg for a in fn.args.posonlyargs + fn.args.args
                      + fn.args.kwonlyargs}
            traced = params - static - {"self", "cls"}
            for node, name in _branch_hazards(fn, traced):
                kind = "if" if isinstance(node, ast.If) else "while"
                yield Finding(
                    ctx.rel, node.lineno, self.id,
                    f"Python `{kind}` on traced argument {name!r} inside a "
                    "jitted function — use lax.cond/lax.while_loop or mark "
                    "the argument static",
                )
