"""peer-json-shape: unguarded shape access on HTTP-response JSON inside
failover try blocks.

The peer/registry failover contract is "a broken peer degrades, it never
kills the pull". With modern ``requests`` a malformed *body* surfaces as
``RequestException`` — but a peer answering 200 with the wrong *shape*
(a captive portal's HTML-as-string, a list where a dict is expected, a
missing key) raises ``ValueError``/``TypeError``/``KeyError``/
``AttributeError`` from the access, escapes a handler that only catches
network errors, and crashes the whole pull.

This pass flags ``try`` blocks that (a) call ``<response>.json()``,
(b) access the result's shape (subscript, method call, or iteration) in
the same block, and (c) have no handler covering ``ValueError`` and
``TypeError`` (or a broader class).
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.analyze.core import Finding, ModuleContext, Pass, dotted, register

_BROAD = {"Exception", "BaseException"}


def _caught(handlers: list[ast.ExceptHandler]) -> set[str]:
    out: set[str] = set()
    for h in handlers:
        t = h.type
        nodes = t.elts if isinstance(t, ast.Tuple) else ([t] if t else [])
        for n in nodes:
            name = dotted(n)
            if name:
                out.add(name.split(".")[-1])
    return out


def _shape_guarded(caught: set[str]) -> bool:
    if caught & _BROAD:
        return True
    return {"ValueError", "TypeError"} <= caught


@register
class JsonShapePass(Pass):
    id = "peer-json-shape"
    description = (
        "response.json() shape-accessed in a failover try whose handlers "
        "catch neither ValueError nor TypeError — junk from a peer crashes "
        "the pull instead of failing over"
    )

    def visit(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Try):
                continue
            if not node.handlers:
                # try/finally catches nothing — guarding (or not) is the
                # enclosing try's business, which gets its own visit
                continue
            if _shape_guarded(_caught(node.handlers)):
                continue
            yield from self._scan_body(ctx, node)

    def _scan_body(self, ctx: ModuleContext,
                   node: ast.Try) -> Iterator[Finding]:
        # taint: names assigned from `<x>.json()` within this try body
        tainted: set[str] = set()
        body_nodes: list[ast.AST] = []
        for stmt in node.body:
            body_nodes.extend(ast.walk(stmt))
        for sub in body_nodes:
            if isinstance(sub, ast.Assign) and self._is_json_call(sub.value):
                for tgt in sub.targets:
                    if isinstance(tgt, ast.Name):
                        tainted.add(tgt.id)
            # one propagation step: x = <tainted>.get(...) etc.
        if not tainted and not any(
            self._is_json_call(s) for s in body_nodes
        ):
            return
        # propagate through single method-call/subscript assignments
        changed = True
        while changed:
            changed = False
            for sub in body_nodes:
                if isinstance(sub, ast.Assign) \
                        and isinstance(sub.value, (ast.Call, ast.Subscript)) \
                        and self._root_name(sub.value) in tainted:
                    for tgt in sub.targets:
                        if isinstance(tgt, ast.Name) and tgt.id not in tainted:
                            tainted.add(tgt.id)
                            changed = True
                if isinstance(sub, ast.For) and isinstance(sub.target,
                                                           ast.Name) \
                        and self._root_name(sub.iter) in tainted \
                        and sub.target.id not in tainted:
                    tainted.add(sub.target.id)
                    changed = True
        seen_lines: set[int] = set()
        for sub in body_nodes:
            access = self._shape_access(sub, tainted)
            if not access:
                continue
            # ast.comprehension carries no lineno — use its iterable's
            line = getattr(sub, "lineno", None) or sub.iter.lineno
            if line not in seen_lines:
                seen_lines.add(line)
                yield Finding(
                    ctx.rel, line, self.id,
                    f"{access} on response JSON, but the handlers catch "
                    "neither ValueError nor TypeError — malformed peer "
                    "output escapes the failover",
                )

    @staticmethod
    def _is_json_call(n: ast.AST) -> bool:
        return (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
                and n.func.attr == "json" and not n.args and not n.keywords)

    @classmethod
    def _root_name(cls, n: ast.AST) -> str | None:
        """Leftmost Name of a call/subscript/attribute chain (also sees
        through ``x.json()`` receivers)."""
        while True:
            if isinstance(n, ast.Call):
                n = n.func
            elif isinstance(n, (ast.Attribute, ast.Subscript)):
                n = n.value
            elif isinstance(n, ast.Name):
                return n.id
            else:
                return None

    def _shape_access(self, n: ast.AST, tainted: set[str]) -> str | None:
        def is_tainted(v: ast.AST) -> bool:
            return (isinstance(v, ast.Name) and v.id in tainted) \
                or self._is_json_call(v)

        if isinstance(n, ast.Subscript) and is_tainted(n.value):
            return "subscript access"
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and is_tainted(n.func.value) and n.func.attr != "json":
            return f".{n.func.attr}() call"
        if isinstance(n, (ast.For, ast.comprehension)) \
                and is_tainted(n.iter):
            return "iteration"
        return None
