"""no-blocking-io-under-lock: flag blocking I/O inside a ``with <lock>:``
body.

A node-wide lock held across a network round-trip or disk write turns one
slow peer into a convoy: every thread needing the lock (store commits,
index refreshes, metric scrapes) queues behind the I/O. Direct calls are
flagged, plus calls under the lock that the ProjectIndex resolves — any
module, bounded call depth — to a function whose effect summary says it
performs blocking I/O.

Single-flight patterns (a dedicated per-key lock serializing exactly the
I/O it guards, like ``PeerSet.index``) are legitimate; annotate them with
``# demodel: allow(no-blocking-io-under-lock)`` and say why.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from tools.analyze.core import (
    Finding,
    ModuleContext,
    Pass,
    register,
    walk_in_scope,
)
from tools.analyze.index import blocking_call

_LOCKISH_RE = re.compile(r"lock|mutex", re.IGNORECASE)


def _is_lock_ctx(src: str) -> bool:
    return bool(_LOCKISH_RE.search(src))


@register
class LockIoPass(Pass):
    id = "no-blocking-io-under-lock"
    description = (
        "network/disk/sleep calls inside a `with <lock>:` body, directly "
        "or through the project call graph (store/peer/delivery convoy "
        "hazard)"
    )

    def visit(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            lock_srcs = [ctx.src(item.context_expr) for item in node.items]
            if not any(_is_lock_ctx(s) for s in lock_srcs):
                continue
            lock_desc = next(s for s in lock_srcs if _is_lock_ctx(s))
            for sub in walk_in_scope(node):
                if not isinstance(sub, ast.Call):
                    continue
                why = blocking_call(sub, ctx)
                if why is not None:
                    yield Finding(
                        ctx.rel, sub.lineno, self.id,
                        f"blocking {why} while holding {lock_desc}",
                    )
                    continue
                callee = self.index.resolve_in(ctx.rel, sub) \
                    if self.index is not None else None
                if callee is None:
                    continue
                hit = self.index.blocking(callee)
                if hit is not None:
                    line, io_why, via = hit
                    through = "" if via == callee else f" via {via}"
                    yield Finding(
                        ctx.rel, sub.lineno, self.id,
                        f"call to {callee}(){through} (blocking {io_why} at "
                        f"line {line}) while holding {lock_desc}",
                    )
