"""no-blocking-io-under-lock: flag blocking I/O lexically inside a
``with <lock>:`` body.

A node-wide lock held across a network round-trip or disk write turns one
slow peer into a convoy: every thread needing the lock (store commits,
index refreshes, metric scrapes) queues behind the I/O. Direct calls are
flagged, plus one level of intra-module resolution — a call under the
lock to a same-module function / same-class method that itself performs
blocking I/O.

Single-flight patterns (a dedicated per-key lock serializing exactly the
I/O it guards, like ``PeerSet.index``) are legitimate; annotate them with
``# demodel: allow(no-blocking-io-under-lock)`` and say why.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from tools.analyze.core import (
    Finding,
    ModuleContext,
    Pass,
    dotted,
    enclosing_class,
    register,
    walk_in_scope,
)

_LOCKISH_RE = re.compile(r"lock|mutex", re.IGNORECASE)

_BLOCKING_PREFIXES = ("requests.", "subprocess.", "socket.",
                      "urllib.request.")
_BLOCKING_EXACT = {"time.sleep", "open", "urlopen"}
#: method names that block regardless of receiver
_BLOCKING_ATTRS = {"recv", "recvfrom", "sendall", "accept", "makefile",
                   "read_bytes", "write_bytes", "read_text", "write_text"}
#: HTTP verbs — blocking when the receiver looks like an HTTP session
_HTTP_VERBS = {"get", "post", "put", "patch", "delete", "head", "request"}


def _is_lock_ctx(src: str) -> bool:
    return bool(_LOCKISH_RE.search(src))


def _blocking_call(node: ast.Call, ctx: ModuleContext) -> str | None:
    """Why this call blocks, or None."""
    name = dotted(node.func)
    if name:
        if name in _BLOCKING_EXACT:
            return f"{name}()"
        if name.startswith(_BLOCKING_PREFIXES):
            return f"{name}()"
    if isinstance(node.func, ast.Attribute):
        attr = node.func.attr
        recv = ctx.src(node.func.value)
        if attr in _BLOCKING_ATTRS:
            return f".{attr}() on {recv}"
        if attr in _HTTP_VERBS and "session" in recv.lower():
            return f"HTTP {attr}() on {recv}"
    return None


def _local_blocking_callables(ctx: ModuleContext) -> dict[str, int]:
    """``name`` / ``Class.name`` → line of the blocking call inside it, for
    every function/method in this module that directly performs blocking
    I/O (one level of propagation, no recursion)."""
    out: dict[str, int] = {}
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        # scope-limited walk: I/O inside a nested def (a worker closure)
        # does not run when THIS function is called under a lock
        for sub in walk_in_scope(node):
            if isinstance(sub, ast.Call):
                why = _blocking_call(sub, ctx)
                if why is not None:
                    cls = enclosing_class(node)
                    key = f"{cls.name}.{node.name}" if cls else node.name
                    out.setdefault(key, sub.lineno)
                    break
    return out


@register
class LockIoPass(Pass):
    id = "no-blocking-io-under-lock"
    description = (
        "network/disk/sleep calls lexically inside a `with <lock>:` body "
        "(store/peer/delivery convoy hazard)"
    )

    def visit(self, ctx: ModuleContext) -> Iterator[Finding]:
        blocking_fns = _local_blocking_callables(ctx)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            lock_srcs = [ctx.src(item.context_expr) for item in node.items]
            if not any(_is_lock_ctx(s) for s in lock_srcs):
                continue
            lock_desc = next(s for s in lock_srcs if _is_lock_ctx(s))
            for sub in walk_in_scope(node):
                if not isinstance(sub, ast.Call):
                    continue
                why = _blocking_call(sub, ctx)
                if why is not None:
                    yield Finding(
                        ctx.rel, sub.lineno, self.id,
                        f"blocking {why} while holding {lock_desc}",
                    )
                    continue
                callee = self._resolve_local(sub, ctx)
                if callee is not None and callee in blocking_fns:
                    yield Finding(
                        ctx.rel, sub.lineno, self.id,
                        f"call to {callee}() (which performs blocking I/O, "
                        f"see line {blocking_fns[callee]}) while holding "
                        f"{lock_desc}",
                    )

    @staticmethod
    def _resolve_local(node: ast.Call, ctx: ModuleContext) -> str | None:
        """Map a call to a same-module function / same-class method key."""
        if isinstance(node.func, ast.Name):
            return node.func.id
        if (isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "self"):
            cls = enclosing_class(node)
            if cls is not None:
                return f"{cls.name}.{node.func.attr}"
        return None
