"""lock-order: build the module-level lock graph and flag cycles.

Nodes are normalized lock identities (``module.Class.attr`` for
``self._lock``-style members, ``module.func.name`` for locals). Edges:

- **lexical** — ``with B:`` nested inside ``with A:`` in one function
  (A held while B is acquired);
- **one-level interprocedural** — under ``with A:``, a call to a
  same-module function / same-class method that acquires any lock B
  anywhere in its body.

Any cycle in that graph is a potential deadlock between the store, the
peer plane, and the restore control plane — exactly the kind TSan only
catches when the interleaving actually happens. Self-edges (re-entering
a non-reentrant ``threading.Lock``) are cycles of length 1.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from tools.analyze.core import (
    Finding,
    ModuleContext,
    Pass,
    enclosing_class,
    enclosing_function,
    register,
    walk_in_scope,
)

_LOCKISH_RE = re.compile(r"lock|mutex", re.IGNORECASE)


def _lock_id(ctx: ModuleContext, expr: ast.AST) -> str | None:
    """Normalized lock identity, or None when the context expr is not
    lock-shaped."""
    src = ctx.src(expr)
    if not _LOCKISH_RE.search(src):
        return None
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name) \
            and expr.value.id == "self":
        cls = enclosing_class(expr)
        scope = cls.name if cls else "<module>"
        return f"{ctx.module}.{scope}.{expr.attr}"
    if isinstance(expr, ast.Name):
        fn = enclosing_function(expr)
        if fn is not None and any(
            isinstance(n, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == expr.id
                for t in n.targets
            )
            for n in ast.walk(fn)
        ):
            # function-local lock (e.g. a per-key single-flight lock)
            return f"{ctx.module}.{fn.name}.{expr.id}"
        return f"{ctx.module}.{expr.id}"
    return f"{ctx.module}.{src}"


class _ModuleFacts:
    def __init__(self) -> None:
        #: callable key ("Class.name" or "name") → locks acquired anywhere
        self.acquires: dict[str, set[str]] = {}
        #: lock → set of (lock, rel, line) edges
        self.edges: dict[str, set[tuple[str, str, int]]] = {}
        #: (holding lock, callable key, rel, line) — resolved in finalize
        self.calls_under: list[tuple[str, str, str, int]] = []


@register
class LockOrderPass(Pass):
    id = "lock-order"
    description = (
        "cycles in the module-level lock acquisition graph "
        "(potential deadlocks across store/peer/restore)"
    )

    def __init__(self) -> None:
        self._facts: list[_ModuleFacts] = []

    def visit(self, ctx: ModuleContext) -> Iterator[Finding]:
        facts = _ModuleFacts()
        self._facts.append(facts)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            held = [
                lid for item in node.items
                if (lid := _lock_id(ctx, item.context_expr)) is not None
            ]
            if not held:
                continue
            fn = enclosing_function(node)
            if fn is not None:
                cls = enclosing_class(fn)
                key = f"{cls.name}.{fn.name}" if cls else fn.name
                facts.acquires.setdefault(key, set()).update(held)
            for sub in walk_in_scope(node):
                if isinstance(sub, (ast.With, ast.AsyncWith)):
                    for item in sub.items:
                        inner = _lock_id(ctx, item.context_expr)
                        if inner is not None:
                            for h in held:
                                facts.edges.setdefault(h, set()).add(
                                    (inner, ctx.rel, sub.lineno))
                elif isinstance(sub, ast.Call):
                    callee = self._callee_key(sub)
                    if callee is not None:
                        for h in held:
                            facts.calls_under.append(
                                (h, callee, ctx.rel, sub.lineno))
        return iter(())

    @staticmethod
    def _callee_key(node: ast.Call) -> str | None:
        if isinstance(node.func, ast.Name):
            return node.func.id
        if (isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "self"):
            cls = enclosing_class(node)
            if cls is not None:
                return f"{cls.name}.{node.func.attr}"
        return None

    def finalize(self) -> Iterator[Finding]:
        edges: dict[str, set[tuple[str, str, int]]] = {}
        for facts in self._facts:
            for a, outs in facts.edges.items():
                edges.setdefault(a, set()).update(outs)
            for held, callee, rel, line in facts.calls_under:
                for b in facts.acquires.get(callee, ()):
                    edges.setdefault(held, set()).add((b, rel, line))
        # cycle detection over the lock graph
        graph = {a: {b for b, _, _ in outs} for a, outs in edges.items()}
        site = {(a, b): (rel, line)
                for a, outs in edges.items() for b, rel, line in outs}
        reported: set[frozenset[str]] = set()
        for start in sorted(graph):
            cycle = self._find_cycle(graph, start)
            if cycle is None:
                continue
            sig = frozenset(cycle)
            if sig in reported:
                continue
            reported.add(sig)
            rel, line = site[(cycle[0], cycle[1])] if len(cycle) > 1 \
                else site[(cycle[0], cycle[0])]
            path = " -> ".join(cycle + [cycle[0]])
            yield Finding(
                rel, line, self.id,
                f"lock acquisition cycle: {path} — a concurrent pair of "
                "these call paths can deadlock",
            )

    @staticmethod
    def _find_cycle(graph: dict[str, set[str]],
                    start: str) -> list[str] | None:
        """DFS from ``start``; returns the node path of a cycle through
        ``start`` (self-edges give a length-1 path)."""
        stack: list[tuple[str, list[str]]] = [(start, [start])]
        seen: set[str] = set()
        while stack:
            node, path = stack.pop()
            for nxt in sorted(graph.get(node, ())):
                if nxt == start:
                    return path
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None
