"""lock-order: build the project-wide lock graph and flag cycles.

Nodes are normalized lock identities (``module.Class.attr`` for
``self._lock``-style members, ``module.func.name`` for locals). Edges:

- **lexical** — ``with B:`` nested inside ``with A:`` in one function
  (A held while B is acquired);
- **interprocedural** — under ``with A:``, a call the ProjectIndex
  resolves (any module, bounded call depth) to a function whose summary
  acquires any lock B.

Any cycle in that graph is a potential deadlock between the store, the
peer plane, and the restore control plane — exactly the kind TSan only
catches when the interleaving actually happens. Self-edges (re-entering
a non-reentrant ``threading.Lock``) are cycles of length 1.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.analyze.core import (
    Finding,
    ModuleContext,
    Pass,
    enclosing_class,
    enclosing_function,
    register,
    walk_in_scope,
)
from tools.analyze.index import lock_id


@register
class LockOrderPass(Pass):
    id = "lock-order"
    description = (
        "cycles in the project-wide lock acquisition graph "
        "(potential deadlocks across store/peer/restore)"
    )

    def __init__(self) -> None:
        super().__init__()
        #: lock → set of (lock, rel, line) edges
        self._edges: dict[str, set[tuple[str, str, int]]] = {}

    def visit(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            fn = enclosing_function(node)
            cls = enclosing_class(node)
            aliases = self.index.aliases.get(ctx.module) \
                if self.index is not None else None
            held = [
                lid for item in node.items
                if (lid := lock_id(ctx, item.context_expr, cls, fn,
                                   aliases))
                is not None
            ]
            if not held:
                continue
            for sub in walk_in_scope(node):
                if isinstance(sub, (ast.With, ast.AsyncWith)):
                    sfn = enclosing_function(sub)
                    scls = enclosing_class(sub)
                    for item in sub.items:
                        inner = lock_id(ctx, item.context_expr, scls, sfn,
                                        aliases)
                        if inner is not None:
                            for h in held:
                                self._edges.setdefault(h, set()).add(
                                    (inner, ctx.rel, sub.lineno))
                elif isinstance(sub, ast.Call) and self.index is not None:
                    callee = self.index.resolve_in(ctx.rel, sub)
                    if callee is None:
                        continue
                    for b in self.index.acquired_locks(callee):
                        for h in held:
                            self._edges.setdefault(h, set()).add(
                                (b, ctx.rel, sub.lineno))
        return iter(())

    def finalize(self) -> Iterator[Finding]:
        edges = self._edges
        # cycle detection over the lock graph
        graph = {a: {b for b, _, _ in outs} for a, outs in edges.items()}
        site = {(a, b): (rel, line)
                for a, outs in edges.items() for b, rel, line in outs}
        reported: set[frozenset[str]] = set()
        for start in sorted(graph):
            cycle = self._find_cycle(graph, start)
            if cycle is None:
                continue
            sig = frozenset(cycle)
            if sig in reported:
                continue
            reported.add(sig)
            rel, line = site[(cycle[0], cycle[1])] if len(cycle) > 1 \
                else site[(cycle[0], cycle[0])]
            path = " -> ".join(cycle + [cycle[0]])
            yield Finding(
                rel, line, self.id,
                f"lock acquisition cycle: {path} — a concurrent pair of "
                "these call paths can deadlock",
            )

    @staticmethod
    def _find_cycle(graph: dict[str, set[str]],
                    start: str) -> list[str] | None:
        """DFS from ``start``; returns the node path of a cycle through
        ``start`` (self-edges give a length-1 path)."""
        stack: list[tuple[str, list[str]]] = [(start, [start])]
        seen: set[str] = set()
        while stack:
            node, path = stack.pop()
            for nxt in sorted(graph.get(node, ())):
                if nxt == start:
                    return path
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None
