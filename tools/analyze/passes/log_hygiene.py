"""log-hygiene: eagerly-formatted log calls.

``log.debug(f"...{x}...")`` (or ``%``-/``.format()``-/concatenation-
formatted first arguments) pay the formatting cost even when the record
is filtered out. On per-chunk/per-request paths that work shows up in
profiles; the logging module's lazy form ``log.debug("...%s...", x)``
formats only when the record is actually emitted.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.analyze.core import Finding, ModuleContext, Pass, register

_LEVELS = {"debug", "info", "warning", "error", "exception", "critical"}


def _is_logger(recv: ast.AST) -> bool:
    if isinstance(recv, ast.Name):
        return recv.id in ("log", "logger") or recv.id.endswith("log")
    if isinstance(recv, ast.Attribute):
        return recv.attr in ("log", "logger") or recv.attr.endswith("_log")
    return False


def _eager_kind(arg: ast.AST) -> str | None:
    if isinstance(arg, ast.JoinedStr):
        return "f-string"
    if isinstance(arg, ast.BinOp):
        if isinstance(arg.op, ast.Mod):
            return "%-interpolation"
        if isinstance(arg.op, ast.Add):
            return "string concatenation"
    if isinstance(arg, ast.Call) and isinstance(arg.func, ast.Attribute) \
            and arg.func.attr == "format":
        return ".format() call"
    return None


@register
class LogHygienePass(Pass):
    id = "log-hygiene"
    description = (
        "eagerly-formatted log calls (f-string/%/.format/concat) — use the "
        "lazy `log.level(\"..%s..\", x)` form"
    )

    def visit(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _LEVELS
                    and _is_logger(node.func.value)
                    and node.args):
                continue
            kind = _eager_kind(node.args[0])
            if kind is not None:
                yield Finding(
                    ctx.rel, node.lineno, self.id,
                    f"{kind} formats eagerly even when the record is "
                    "filtered — pass args lazily",
                )
