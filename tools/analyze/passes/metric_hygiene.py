"""metric-hygiene: non-literal metric names on the Hub surface.

``HUB.inc(f"pull_{source}_total")`` mints a new time series per distinct
value — unbounded cardinality that bloats every scrape and breaks
aggregation (you cannot ``sum()`` a family you cannot name). The contract:
metric NAMES passed to ``Hub.inc`` / ``Hub.set_gauge`` / ``Hub.observe``
are literal snake_case strings, and anything dynamic (peer, span, route)
goes through ``metrics.labeled(<literal>, key=value)`` — labels are the
bounded, queryable place for variance.

The rule resolves through the benign indirections the tree actually uses:
a local/module name bound to a literal (``name = "peer_retries_total"``),
an ``IfExp`` whose both arms resolve, and ``labeled(...)`` calls (whose
first argument must itself resolve). Everything else — f-strings,
``%``/``+``/``.format`` composition, names bound to expressions — is a
finding.

The READ side has the inverse hazard: the telemetry plane's windowed
views (``rate`` / ``window_quantile`` / ``family_rate`` / ``series`` /
``window_delta``) look families up by name, and a typo'd name doesn't
raise — it silently returns an empty window, which a consumer like the
adaptive pull tuner would read as "all quiet" forever. So read-site
names must (a) resolve to literals exactly like write-site names, and
(b) name a family some ``inc``/``set_gauge``/``observe`` write in the
analyzed tree actually registers (checked in :meth:`finalize`, once the
whole run's write set is known). Retention-plane history queries
(``archive.history(family=...)``) have the same failure mode — a typo'd
family filter returns an empty (not wrong) series from a full archive —
and get the same check; a filterless ``history()`` is fine. The profiler
plane's ``archive.profiles(plane=...)`` filter is checked against the
two planes that exist (``python`` / ``native``): a typo'd plane silently
reads as "no profiles archived".

Scope: files under ``demodel_tpu/`` plus any file carrying an explicit
``# demodel: metrics-plane`` pragma (how the golden fixture opts in).
Write-site names are COLLECTED from every module in the run (benches and
tests register families too); the planes themselves
(``demodel_tpu/utils/metrics.py``, ``demodel_tpu/utils/retention.py``)
are exempt from the read check — their methods pass caller-supplied
names through parameters.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from tools.analyze.core import (
    Finding,
    ModuleContext,
    Pass,
    dotted,
    enclosing_function,
    register,
)

_METHODS = {"inc", "set_gauge", "observe"}
#: windowed-view lookups whose name arg silently yields an empty window
#: when it names a family nothing registers
_READS = {"rate", "window_quantile", "family_rate", "series",
          "window_delta"}
#: receivers a read call counts under: the hub itself or a telemetry
#: ring (``tel`` is the tree's idiomatic local for one)
_READ_RECEIVERS = {"HUB", "hub", "tel", "telemetry"}
#: receivers a ``history(family=...)`` lookup counts under — the tree's
#: idiomatic locals for a TelemetryArchive
_HISTORY_RECEIVERS = {"archive", "ARCHIVE"}
#: the planes themselves — their forwarding methods take names as
#: parameters
_PLANES = {"demodel_tpu/utils/metrics.py",
           "demodel_tpu/utils/retention.py"}
_PRAGMA = "# demodel: metrics-plane"
_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
#: the only planes the profiler plane records windows under
_PROFILE_PLANES = {"python", "native"}


def _is_labeled_call(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id == "labeled"
    return isinstance(f, ast.Attribute) and f.attr == "labeled"


def _assignments_of(name: str, *scopes: ast.AST) -> list[ast.expr]:
    """Every ``name = <expr>`` in the given scopes (function body first,
    then module top level — the two places the tree binds metric names)."""
    out: list[ast.expr] = []
    for scope in scopes:
        if scope is None:
            continue
        body = getattr(scope, "body", [])
        nodes = (list(ast.walk(scope))
                 if not isinstance(scope, ast.Module) else body)
        for node in nodes:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == name:
                out.append(node.value)
            elif isinstance(node, ast.AnnAssign) and node.value is not None \
                    and isinstance(node.target, ast.Name) \
                    and node.target.id == name:
                out.append(node.value)
    return out


class _Resolver:
    """Resolves a metric-name expression to "fine" (None) or a reason
    string, chasing names with a cycle guard."""

    def __init__(self, call: ast.Call, ctx: ModuleContext) -> None:
        self.fn = enclosing_function(call)
        self.ctx = ctx
        self.seen: set[str] = set()
        #: every base family literal the expression resolves through —
        #: only meaningful when :meth:`resolve` returned None (fine)
        self.names: set[str] = set()

    def resolve(self, expr: ast.expr) -> str | None:
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            if not _NAME_RE.match(expr.value):
                return (f"metric name {expr.value!r} is not snake_case — "
                        "labels belong in labeled(), not the name")
            self.names.add(expr.value)
            return None
        if isinstance(expr, ast.Call) and _is_labeled_call(expr):
            if not expr.args:
                return "labeled() without a metric name"
            return self.resolve(expr.args[0])
        if isinstance(expr, ast.IfExp):
            return self.resolve(expr.body) or self.resolve(expr.orelse)
        if isinstance(expr, ast.JoinedStr):
            return ("f-string metric name mints a series per value — "
                    "unbounded cardinality; use labeled()")
        if isinstance(expr, ast.Name):
            if expr.id in self.seen:
                # cycle along the CURRENT resolution chain only — the same
                # name may legitimately appear in both arms of an IfExp
                return f"metric name {expr.id!r} is not a literal"
            self.seen.add(expr.id)
            try:
                assigns = _assignments_of(expr.id, self.fn, self.ctx.tree)
                if not assigns:
                    return (f"metric name {expr.id!r} does not resolve to "
                            "a literal in this scope")
                for value in assigns:
                    reason = self.resolve(value)
                    if reason:
                        return reason
                return None
            finally:
                self.seen.discard(expr.id)
        return ("computed metric name (%/+/.format/expression) — "
                "names must be literal snake_case, variance via labeled()")


def _is_read_receiver(value: ast.expr) -> bool:
    """The hub, a telemetry local, or a ``...telemetry()`` call chain."""
    recv = dotted(value)
    if recv is not None:
        return recv.rsplit(".", 1)[-1] in _READ_RECEIVERS
    if isinstance(value, ast.Call):
        f = dotted(value.func)
        return f is not None and f.rsplit(".", 1)[-1] == "telemetry"
    return False


def _is_history_receiver(value: ast.expr) -> bool:
    """A TelemetryArchive local, or a ``retention.current()`` /
    ``retention.ensure()`` call chain."""
    recv = dotted(value)
    if recv is not None:
        return recv.rsplit(".", 1)[-1] in _HISTORY_RECEIVERS
    if isinstance(value, ast.Call):
        f = dotted(value.func)
        return f is not None and f.rsplit(".", 1)[-1] in ("current", "ensure")
    return False


@register
class MetricHygienePass(Pass):
    id = "metric-hygiene"
    description = (
        "metric names passed to Hub.inc/set_gauge/observe must be literal "
        "snake_case (labels only via metrics.labeled) — dynamic names are "
        "unbounded scrape cardinality; telemetry reads (rate/"
        "window_quantile/...) and archive history(family=...) lookups "
        "must name a family some write registers — a typo'd read "
        "silently returns an empty window"
    )

    def __init__(self) -> None:
        super().__init__()
        self._written: set[str] = set()
        self._reads: list[tuple[str, int, str]] = []

    def visit(self, ctx: ModuleContext) -> Iterator[Finding]:
        in_scope = (ctx.rel.startswith("demodel_tpu/")
                    or _PRAGMA in ctx.source)
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            attr = node.func.attr
            if attr in _METHODS and node.args:
                recv = dotted(node.func.value)
                if recv is None:
                    continue
                last = recv.rsplit(".", 1)[-1]
                if last not in ("HUB", "hub"):
                    continue
                resolver = _Resolver(node, ctx)
                reason = resolver.resolve(node.args[0])
                if reason:
                    if in_scope:
                        yield Finding(ctx.rel, node.lineno, self.id, reason)
                else:
                    # write-site families register regardless of scope:
                    # benches/tests mint real families too, and the read
                    # check below must not flag them as typos
                    self._written |= resolver.names
            elif attr in _READS and node.args and in_scope \
                    and ctx.rel not in _PLANES \
                    and _is_read_receiver(node.func.value):
                resolver = _Resolver(node, ctx)
                reason = resolver.resolve(node.args[0])
                if reason:
                    yield Finding(ctx.rel, node.lineno, self.id,
                                  f"telemetry read: {reason}")
                else:
                    for name in resolver.names:
                        self._reads.append((ctx.rel, node.lineno, name))
            elif attr == "history" and in_scope \
                    and ctx.rel not in _PLANES \
                    and _is_history_receiver(node.func.value):
                # family filter may arrive positionally or as family=;
                # a filterless history() (or family=None) is fine
                name_expr = node.args[0] if node.args else next(
                    (kw.value for kw in node.keywords
                     if kw.arg == "family"), None)
                if name_expr is None or (
                        isinstance(name_expr, ast.Constant)
                        and name_expr.value is None):
                    continue
                resolver = _Resolver(node, ctx)
                reason = resolver.resolve(name_expr)
                if reason:
                    yield Finding(ctx.rel, node.lineno, self.id,
                                  f"history read: {reason}")
                else:
                    for name in resolver.names:
                        self._reads.append((ctx.rel, node.lineno, name))
            elif attr == "profiles" and in_scope \
                    and ctx.rel not in _PLANES \
                    and _is_history_receiver(node.func.value):
                # plane filter: positional (since, until, plane) or
                # plane=; filterless (or plane=None) reads every plane
                plane_expr = node.args[2] if len(node.args) > 2 else next(
                    (kw.value for kw in node.keywords
                     if kw.arg == "plane"), None)
                if plane_expr is None or (
                        isinstance(plane_expr, ast.Constant)
                        and plane_expr.value is None):
                    continue
                if isinstance(plane_expr, ast.Constant) \
                        and isinstance(plane_expr.value, str):
                    if plane_expr.value not in _PROFILE_PLANES:
                        yield Finding(
                            ctx.rel, node.lineno, self.id,
                            f"profile read of plane {plane_expr.value!r} "
                            "— only "
                            f"{sorted(_PROFILE_PLANES)} exist; the filter "
                            "silently returns zero windows")
                else:
                    yield Finding(
                        ctx.rel, node.lineno, self.id,
                        "profile read: plane filter is not a literal — "
                        "a computed plane that matches nothing reads as "
                        "'no profiles archived'")

    def finalize(self) -> Iterator[Finding]:
        if not self._written:
            # a run with zero write sites is a fragment without the
            # metrics plane — nothing meaningful to validate against
            return
        for rel, line, name in self._reads:
            if name not in self._written:
                yield Finding(
                    rel, line, self.id,
                    f"telemetry read of family {name!r} that no "
                    "Hub.inc/set_gauge/observe in this tree registers — "
                    "the window is silently empty (typo'd name?)")
