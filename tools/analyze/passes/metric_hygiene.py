"""metric-hygiene: non-literal metric names on the Hub surface.

``HUB.inc(f"pull_{source}_total")`` mints a new time series per distinct
value — unbounded cardinality that bloats every scrape and breaks
aggregation (you cannot ``sum()`` a family you cannot name). The contract:
metric NAMES passed to ``Hub.inc`` / ``Hub.set_gauge`` / ``Hub.observe``
are literal snake_case strings, and anything dynamic (peer, span, route)
goes through ``metrics.labeled(<literal>, key=value)`` — labels are the
bounded, queryable place for variance.

The rule resolves through the benign indirections the tree actually uses:
a local/module name bound to a literal (``name = "peer_retries_total"``),
an ``IfExp`` whose both arms resolve, and ``labeled(...)`` calls (whose
first argument must itself resolve). Everything else — f-strings,
``%``/``+``/``.format`` composition, names bound to expressions — is a
finding.

Scope: files under ``demodel_tpu/`` plus any file carrying an explicit
``# demodel: metrics-plane`` pragma (how the golden fixture opts in).
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from tools.analyze.core import (
    Finding,
    ModuleContext,
    Pass,
    dotted,
    enclosing_function,
    register,
)

_METHODS = {"inc", "set_gauge", "observe"}
_PRAGMA = "# demodel: metrics-plane"
_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")


def _is_labeled_call(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id == "labeled"
    return isinstance(f, ast.Attribute) and f.attr == "labeled"


def _assignments_of(name: str, *scopes: ast.AST) -> list[ast.expr]:
    """Every ``name = <expr>`` in the given scopes (function body first,
    then module top level — the two places the tree binds metric names)."""
    out: list[ast.expr] = []
    for scope in scopes:
        if scope is None:
            continue
        body = getattr(scope, "body", [])
        nodes = (list(ast.walk(scope))
                 if not isinstance(scope, ast.Module) else body)
        for node in nodes:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == name:
                out.append(node.value)
            elif isinstance(node, ast.AnnAssign) and node.value is not None \
                    and isinstance(node.target, ast.Name) \
                    and node.target.id == name:
                out.append(node.value)
    return out


class _Resolver:
    """Resolves a metric-name expression to "fine" (None) or a reason
    string, chasing names with a cycle guard."""

    def __init__(self, call: ast.Call, ctx: ModuleContext) -> None:
        self.fn = enclosing_function(call)
        self.ctx = ctx
        self.seen: set[str] = set()

    def resolve(self, expr: ast.expr) -> str | None:
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            if not _NAME_RE.match(expr.value):
                return (f"metric name {expr.value!r} is not snake_case — "
                        "labels belong in labeled(), not the name")
            return None
        if isinstance(expr, ast.Call) and _is_labeled_call(expr):
            if not expr.args:
                return "labeled() without a metric name"
            return self.resolve(expr.args[0])
        if isinstance(expr, ast.IfExp):
            return self.resolve(expr.body) or self.resolve(expr.orelse)
        if isinstance(expr, ast.JoinedStr):
            return ("f-string metric name mints a series per value — "
                    "unbounded cardinality; use labeled()")
        if isinstance(expr, ast.Name):
            if expr.id in self.seen:
                # cycle along the CURRENT resolution chain only — the same
                # name may legitimately appear in both arms of an IfExp
                return f"metric name {expr.id!r} is not a literal"
            self.seen.add(expr.id)
            try:
                assigns = _assignments_of(expr.id, self.fn, self.ctx.tree)
                if not assigns:
                    return (f"metric name {expr.id!r} does not resolve to "
                            "a literal in this scope")
                for value in assigns:
                    reason = self.resolve(value)
                    if reason:
                        return reason
                return None
            finally:
                self.seen.discard(expr.id)
        return ("computed metric name (%/+/.format/expression) — "
                "names must be literal snake_case, variance via labeled()")


@register
class MetricHygienePass(Pass):
    id = "metric-hygiene"
    description = (
        "metric names passed to Hub.inc/set_gauge/observe must be literal "
        "snake_case (labels only via metrics.labeled) — dynamic names are "
        "unbounded scrape cardinality"
    )

    def visit(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not (ctx.rel.startswith("demodel_tpu/")
                or _PRAGMA in ctx.source):
            return
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _METHODS
                    and node.args):
                continue
            recv = dotted(node.func.value)
            if recv is None:
                continue
            last = recv.rsplit(".", 1)[-1]
            if last not in ("HUB", "hub"):
                continue
            reason = _Resolver(node, ctx).resolve(node.args[0])
            if reason:
                yield Finding(ctx.rel, node.lineno, self.id, reason)
