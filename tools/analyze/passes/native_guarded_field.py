"""native-guarded-field: lock-set races over the native concurrency index.

The RacerD shape, ported to the clang-free native plane
(:mod:`tools.analyze.native_concurrency`): every read/write of a data
member in a class that owns a mutex or atomic is summarized with the
lock set held at the site — lexical ``lock_guard``/``unique_lock``/
``scoped_lock`` regions plus the caller-held intersection composed
through the C++ call graph at bounded depth — and with the thread
roots that can reach it (worker pool, reactor loop, accept loop,
sampler, the ``extern "C"`` API surface). A member written on one root
and touched on another with DISJOINT lock sets is a race finding
blaming both sites and both roots. One root races itself only when it
is multi-instance (the worker pool, API callers).

Relaxed-atomic members get the ``atomic-check-then-act`` sub-check: a
branch that tests an atomic and a plain store that rewrites it under
that branch (outside any ``compare_exchange`` discipline) is a lost
update waiting for an interleave.

Silent by construction: members of classes with no synchronization
members at all (the lock-free handoff plane — Session, WriteState —
is reactor-ownership's jurisdiction), accesses in lifecycle functions
(single-threaded around spawn/join), constructors/destructors touching
their OWN class (owned-before-shared), and any site no thread root
reaches — no speculative roots, no speculative edges.
"""

from __future__ import annotations

import re
from typing import Iterator

from tools.analyze.core import Finding, Pass, register
from tools.analyze.native_concurrency import (
    ConcurrencyIndex,
    NativeAnchorMixin,
    fmt_locks,
)


@register
class NativeGuardedFieldPass(NativeAnchorMixin, Pass):
    id = "native-guarded-field"
    version = "1"
    description = (
        "native lock-set races: a C++ class member written on one thread "
        "root and touched on another with disjoint lock sets (lexical "
        "guard regions + caller-held composition through the call "
        "graph), blaming both sites and both roots; plus the "
        "atomic-check-then-act sub-check on relaxed atomics"
    )

    def finalize(self) -> Iterator[Finding]:
        for idx in self.each_index():
            yield from self._races(idx)
            yield from self._check_then_act(idx)

    # ------------------------------------------------------------- races
    def _sites(self, idx: ConcurrencyIndex) -> dict:
        """(cls, member) → [(access, eff locks, roots)] for in-scope
        data members."""
        scoped = {
            cls for cls, mems in idx.classes.items()
            if any(m.kind in ("mutex", "atomic") for m in mems.values())
        }
        out: dict = {}
        for q in sorted(idx.functions):
            fn = idx.functions[q]
            roots = idx.roots_of(q)
            if not roots:
                continue
            for a in fn.accesses:
                if a.atomic or a.cls not in scoped:
                    continue
                if fn.cls == a.cls and fn.short in (a.cls, f"~{a.cls}"):
                    continue  # ctor/dtor of its own class: owned
                out.setdefault((a.cls, a.member), []).append(
                    (a, idx.eff_locks(a), roots))
        return out

    def _races(self, idx: ConcurrencyIndex) -> Iterator[Finding]:
        for (cls, member), sites in sorted(self._sites(idx).items()):
            sites.sort(key=lambda s: (s[0].rel, s[0].line))
            pair = self._racing_pair(idx, sites)
            if pair is None:
                continue
            (w, lw, rw), (a, la, _ra), r1, r2 = pair
            other = "written" if a.write else "read"
            yield Finding(
                w.rel, w.line, self.id,
                f"native field '{member}' of {cls} written here on root "
                f"'{idx.roots[r1].label}' under {fmt_locks(lw)} and "
                f"{other} at {a.rel}:{a.line} on root "
                f"'{idx.roots[r2].label}' under {fmt_locks(la)} — lock "
                "sets are disjoint, so both threads can touch it "
                "concurrently; guard both sites with one mutex or make "
                "the member atomic",
            )

    def _racing_pair(self, idx: ConcurrencyIndex, sites: list):
        for ws in sites:
            if not ws[0].write:
                continue
            for as_ in sites:
                if ws[1] & as_[1]:
                    continue  # a common lock orders them
                rr = self._concurrent(idx, ws[2], as_[2])
                if rr is not None:
                    return ws, as_, rr[0], rr[1]
        return None

    @staticmethod
    def _concurrent(idx: ConcurrencyIndex, rw: set, ra: set):
        for r1 in sorted(rw):
            for r2 in sorted(ra):
                if r1 != r2:
                    return r1, r2
                if idx.roots[r1].multi:
                    return r1, r2
        return None

    # --------------------------------------------------- check-then-act
    def _check_then_act(self, idx: ConcurrencyIndex) -> Iterator[Finding]:
        # atomics whose touches span enough roots to interleave
        root_span: dict = {}
        for q in sorted(idx.functions):
            roots = idx.roots_of(q)
            for a in idx.functions[q].accesses:
                if a.atomic:
                    root_span.setdefault((a.cls, a.member),
                                         set()).update(roots)
        seen: set = set()
        for q in sorted(idx.functions):
            fn = idx.functions[q]
            if fn.lifecycle:
                continue
            cas_members = {
                (a.cls, a.member) for a in fn.accesses
                if a.op.startswith("compare_exchange")
            }
            for a in fn.accesses:
                if not (a.atomic and a.write):
                    continue
                if a.op.startswith(("fetch_", "exchange",
                                    "compare_exchange")):
                    continue
                key = (a.cls, a.member)
                if key in cas_members:
                    continue
                roots = root_span.get(key, set())
                if len(roots) < 2 and not any(
                        idx.roots[r].multi for r in roots):
                    continue
                st = next((s for s in fn.statements
                           if s.line == a.line), None)
                if st is None:
                    continue
                name_re = re.compile(r"\b%s\b" % re.escape(a.member))
                if a.op == "" and not re.search(
                        r"\b%s\s*=[^=]" % re.escape(a.member), st.text):
                    continue  # ++/compound ops are atomic RMW
                if not any(name_re.search(c) for c in st.conds):
                    continue
                site = (a.rel, a.line, a.member)
                if site in seen:
                    continue
                seen.add(site)
                yield Finding(
                    a.rel, a.line, self.id,
                    f"check-then-act on atomic '{a.member}' of {a.cls}: "
                    "the guarding branch tests the atomic and this "
                    "store rewrites it non-atomically — another thread "
                    "can interleave between the load and the store; "
                    "use compare_exchange or a fetch_* RMW",
                )
