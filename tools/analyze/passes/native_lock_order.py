"""native-lock-order: the kRank table as a whole-program static gate.

``native/lock_order.h``'s ranked-mutex shim (DM_LOCK_ORDER_CHECK)
aborts at runtime when a thread acquires a lock whose rank is ≤ the
highest rank it already holds — but only on interleavings the TSan
selftests actually drive. This rule mirrors the same invariant
statically over the concurrency index: every acquisition site's rank
is resolved from the ``kRank*`` table, nested acquisitions are
composed through the call graph at bounded depth, and any edge from a
higher (or equal) rank to a lower one is a finding — no test needs to
drive the path.

Two shapes fire:

- **inversion** — a ``lock_guard``/``unique_lock``/``scoped_lock``
  acquiring rank ``m`` while a lock of rank ``h >= m`` is lexically or
  caller-held; call-site edges blame the caller's acquisition site and
  name the callee path that performs the nested acquisition.
- **unranked member** — a ``std::mutex`` (or rank-capable wrapper with
  no rank brace) declared as a class member: invisible to
  DM_LOCK_ORDER_CHECK, so invisible to the dynamic gate too. Every
  native mutex member must carry a ``kRank*`` or a suppression
  explaining why it is out of the scheme.

Unranked locks contribute no edges (no speculative ranks); unresolved
calls contribute no nesting. The rule is purely structural — it does
not need thread roots, so it also covers code only reachable from
lifecycle functions.
"""

from __future__ import annotations

from typing import Iterator

from tools.analyze.core import Finding, Pass, register
from tools.analyze.native_concurrency import (
    ConcurrencyIndex,
    NativeAnchorMixin,
)


@register
class NativeLockOrderPass(NativeAnchorMixin, Pass):
    id = "native-lock-order"
    version = "1"
    description = (
        "static lock-order gate over the native kRank table: an "
        "acquisition of rank <= an already-held rank (lexically or "
        "composed through the call graph) is an inversion, and a "
        "std::mutex member with no rank wrapper is invisible to "
        "DM_LOCK_ORDER_CHECK"
    )

    def finalize(self) -> Iterator[Finding]:
        for idx in self.each_index():
            yield from self._unranked_members(idx)
            yield from self._inversions(idx)

    def _unranked_members(self, idx: ConcurrencyIndex) -> Iterator[Finding]:
        for cls in sorted(idx.classes):
            for name, mem in sorted(idx.classes[cls].items()):
                if mem.kind == "mutex" and mem.rank is None:
                    yield Finding(
                        mem.rel, mem.line, self.id,
                        f"mutex member '{cls}::{name}' has no kRank "
                        "wrapper — DM_LOCK_ORDER_CHECK and the static "
                        "order gate cannot see it; declare it as "
                        "Mutex with a kRank constant from "
                        "lock_order.h",
                    )

    def _inversions(self, idx: ConcurrencyIndex) -> Iterator[Finding]:
        seen: set = set()
        for q in sorted(idx.functions):
            fn = idx.functions[q]
            caller_held = idx.must_hold(q)
            # intra-function: a guard taken while earlier guards in
            # scope (or caller-held locks) outrank it
            for i, lock, line in fn.guards:
                rm = idx.rank_of(lock)
                if rm is None:
                    continue
                lex = fn.held[i] if i < len(fn.held) else frozenset()
                for h in sorted(lex | caller_held):
                    if h == lock:
                        continue
                    rh = idx.rank_of(h)
                    if rh is None or rm > rh:
                        continue
                    key = (fn.rel, line, h, lock)
                    if key in seen:
                        continue
                    seen.add(key)
                    yield Finding(
                        fn.rel, line, self.id,
                        f"lock-order inversion: '{lock}' (rank {rm}) "
                        f"acquired while holding '{h}' (rank {rh}) — "
                        "ranks must strictly increase down an "
                        "acquisition chain; DM_LOCK_ORDER_CHECK would "
                        "abort here at runtime",
                    )
            # call-site composition: the callee (transitively) acquires
            # a ranked lock while this site holds an equal-or-higher one
            for j, (callee, line, held) in enumerate(fn.calls):
                eff = held | caller_held
                if not eff:
                    continue
                acquired = idx.acquired_within(callee)
                for lock in sorted(acquired):
                    rm = idx.rank_of(lock)
                    if rm is None:
                        continue
                    for h in sorted(eff):
                        if h == lock:
                            continue
                        rh = idx.rank_of(h)
                        if rh is None or rm > rh:
                            continue
                        key = (fn.rel, line, h, lock)
                        if key in seen:
                            continue
                        seen.add(key)
                        path = " -> ".join(
                            (callee,) + acquired[lock])
                        yield Finding(
                            fn.rel, line, self.id,
                            f"lock-order inversion: this call reaches "
                            f"an acquisition of '{lock}' (rank {rm}) "
                            f"via {path} while holding '{h}' (rank "
                            f"{rh}) — ranks must strictly increase "
                            "down an acquisition chain",
                        )
