"""obligation-leak: paired resources must be released on every path.

The Infer/Pulse must-call shape over the ProjectIndex's obligation
facts (:mod:`tools.analyze.obligations`): every acquire of a tracked
resource — budget tickets, flight leases, store partial writers, fds,
mmaps, streamed HTTP responses, spans — must reach a release, or its
ownership must provably move (returned, stored, handed to a callee
that releases or keeps it). Four finding shapes, all blamed at the
acquire site Infer-style:

- **discarded** — the acquire's result is thrown away on the spot;
  nothing can ever release it.
- **never settled** — no release, return, store, or handoff on any
  path out of the function.
- **dropped by callee** — the entity's only escapes are calls to
  resolved project functions, and composing ``transfers-ownership``
  facts through the call graph (bounded depth, same contract as the
  budget summary) shows every one of them drops the parameter: the
  handoff is an illusion and the blame lands back on the acquire.
- **leaks on raise** — the normal path settles, but a may-raise
  statement sits between the acquire and the settle point outside any
  ``try`` whose ``finally``/handler releases the entity.

Receiver-carried budget tickets get the global-discipline variant: an
``acquire``/``charge`` with no local release is fine as long as
SOMETHING in the project releases that receiver (the split
acquire-here-release-there pattern is the design); zero releases
anywhere is the unpaired-obligation finding.

Twin on the native plane: the same rule runs the
:mod:`tools.analyze.native_index` extractor over ``native/*.{h,cc}``
(``mmap/munmap``, fd ``open/close``, ``SSL_new/SSL_free``,
``hot_acquire/hot_release``, epoll registrations), RAII-aware.
Anchoring mirrors surface-parity: the real tree activates via
``demodel_tpu/utils/env.py`` → ``<root>/native``; fixtures via a
``# demodel: obligation-native=<dir>`` pragma.

Everything unresolved stays silent — no speculative leaks.
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import Iterator

from tools.analyze.core import Finding, ModuleContext, Pass, register
from tools.analyze import native_index

_PRAGMA_RE = re.compile(r"#\s*demodel:\s*obligation-native=(\S+)")

#: interprocedural composition depth for transfers-ownership facts —
#: matches the budget summary's contract (deep chains go "unknown",
#: and unknown is silent)
_MAX_DEPTH = 4


@register
class ObligationLeakPass(Pass):
    id = "obligation-leak"
    version = "1"
    description = (
        "paired-resource lifecycle: budget tickets, flight leases, store "
        "partial writers, fds/mmaps, streamed responses and spans must be "
        "released on every path — discarded acquires, never-settled "
        "entities, handoffs to callees that provably drop them, and "
        "raise-paths that skip the release; native twin over "
        "mmap/munmap, open/close, SSL_new/SSL_free, hot pins and epoll "
        "registrations, RAII-aware"
    )

    @classmethod
    def cache_extra_inputs(cls, files) -> list:
        """The native sources this rule scans: their stat triples join
        the cache key so a ``native/*.{h,cc}`` edit alone invalidates
        cached findings (same contract as surface-parity)."""
        dirs: list[Path] = []
        for p in files:
            path = Path(p)
            posix = path.as_posix()
            if posix.endswith("demodel_tpu/utils/env.py"):
                root = Path(posix[: -len("demodel_tpu/utils/env.py")]
                            or ".")
                dirs.append(root / "native")
                continue
            try:
                head = path.read_text(encoding="utf-8",
                                      errors="replace")[:4096]
            except OSError:
                continue
            pm = _PRAGMA_RE.search(head)
            if pm:
                dirs.append(path.parent / pm.group(1))
        out: list[Path] = []
        for d in dirs:
            if d.is_dir():
                out.extend(sorted(d.glob("*.h")))
                out.extend(sorted(d.glob("*.cc")))
        return out

    def __init__(self) -> None:
        super().__init__()
        self._native_dirs: list[tuple[Path, str]] = []

    # ------------------------------------------------------------ visit
    def visit(self, ctx: ModuleContext) -> Iterator[Finding]:
        pm = _PRAGMA_RE.search(ctx.source)
        if pm:
            self._native_dirs.append(
                (Path(ctx.path).resolve().parent / pm.group(1),
                 ctx.rel.rsplit("/", 1)[0] + "/" + pm.group(1) + "/"
                 if "/" in ctx.rel else pm.group(1) + "/"))
        elif ctx.rel == "demodel_tpu/utils/env.py":
            root = Path(str(Path(ctx.path).resolve())[: -len(ctx.rel)]) \
                if str(Path(ctx.path).resolve()).endswith(ctx.rel) \
                else Path.cwd()
            self._native_dirs.append((root / "native", "native/"))
        return iter(())

    # --------------------------------------------------------- finalize
    def finalize(self) -> Iterator[Finding]:
        yield from self._python_plane()
        seen: set[Path] = set()
        for native_dir, prefix in self._native_dirs:
            if native_dir in seen or not native_dir.is_dir():
                continue
            seen.add(native_dir)
            yield from self._native_plane(native_dir, prefix)

    # ------------------------------------------------- the Python plane
    def _python_plane(self) -> Iterator[Finding]:
        released_global = self._released_receivers_by_class()
        for qname in sorted(self.index.functions):
            info = self.index.functions[qname]
            for site in info.obligations:
                yield from self._judge(qname, info, site, released_global)

    def _released_receivers_by_class(self) -> dict:
        """cls qname (or "" for free functions) → receiver texts some
        method releases — the global side of the receiver-carried
        discipline."""
        out: dict[str, set[str]] = {}
        for info in self.index.functions.values():
            key = info.cls or ""
            out.setdefault(key, set()).update(info.released_receivers)
        return out

    def _judge(self, qname, info, site, released_global) -> Iterator[Finding]:
        short = qname.rsplit(".", 1)[-1]
        if site.discarded:
            yield Finding(
                info.rel, site.line, self.id,
                f"{site.label} acquired by `{site.acquire_src}` and the "
                f"result is discarded — nothing can ever release it; "
                "bind it and release in a finally, or use `with`",
            )
            return
        if site.carrier == "receiver":
            yield from self._judge_receiver(info, site, released_global,
                                            short)
            return
        settle = site.settle
        if settle is None and not site.forwards:
            yield Finding(
                info.rel, site.line, self.id,
                f"{site.label} bound to `{site.entity}` here is never "
                f"released, returned, or stored on any path out of "
                f"{short}() — leaked unconditionally",
            )
            return
        if settle is None:
            # every escape is a resolved-callee handoff: compose the
            # callees' transfers-ownership facts
            fates = [self._fate(q, param, 0, set())
                     for q, param, _line in site.forwards]
            if fates and all(f == "dropped" for f in fates):
                q, param, line = site.forwards[0]
                callee = q.rsplit(".", 1)[-1]
                yield Finding(
                    info.rel, site.line, self.id,
                    f"{site.label} bound to `{site.entity}` here is "
                    f"handed to {callee}() (line {line}) which neither "
                    f"releases nor keeps parameter `{param}` — the "
                    "obligation is dropped in the callee; release it "
                    f"here or make {callee}() take ownership",
                )
            return
        if settle[0] == "transfer" and settle[1] == "rebound":
            return  # rebinding starts a new epoch: silent by contract
        yield from self._risky(info, site, short)

    def _judge_receiver(self, info, site, released_global,
                        short) -> Iterator[Finding]:
        settle = site.settle
        if settle is not None and settle[0] == "discharge":
            # acquire and release in one body: the path between them
            # must be protected (the PR-3 leaked-ticket shape)
            yield from self._risky(info, site, short)
            return
        if settle is not None:
            return  # receiver transferred/rebound: out of scope
        recv = site.entity
        tail = recv.rsplit(".", 1)[-1]
        pools = [released_global.get(info.cls or "", set())] \
            if info.cls else []
        pools.append({r for s in released_global.values() for r in s})
        for pool in pools:
            if recv in pool or any(r.rsplit(".", 1)[-1] == tail
                                   for r in pool):
                return  # something in the project releases this receiver
        yield Finding(
            info.rel, site.line, self.id,
            f"{site.label} charged on `{recv}` in {short}() but nothing "
            f"in the project ever releases `{recv}` — an unpaired "
            "obligation; every acquire/charge needs a release/abort "
            "somewhere",
        )

    def _risky(self, info, site, short) -> Iterator[Finding]:
        if not site.risky:
            return
        line, src = site.risky[0]
        settle = site.settle
        how = f"the release at line {settle[1]}" if settle[0] == \
            "discharge" else f"the handoff at line {settle[-1]}"
        more = f" (+{len(site.risky) - 1} more such lines)" \
            if len(site.risky) > 1 else ""
        yield Finding(
            info.rel, site.line, self.id,
            f"{site.label} bound to `{site.entity}` here leaks if "
            f"`{src}` (line {line}){more} raises before {how} — wrap "
            "the risky region in try/finally or release in an except",
        )

    def _fate(self, q, param, depth, seen) -> str:
        """What a callee does with an obligation handed to ``param`` —
        "settled" (released or kept), "dropped", or "unknown" (silent).
        Follows forwarded params through the call graph to _MAX_DEPTH,
        the same bounded composition the budget summary uses."""
        if depth > _MAX_DEPTH or (q, param) in seen:
            return "unknown"
        seen.add((q, param))
        info = self.index.functions.get(q)
        if info is None:
            return "unknown"
        fate = info.param_fate.get(param)
        if fate is None:
            return "unknown"
        if fate[0] == "forwarded":
            return self._fate(fate[1], fate[2], depth + 1, seen)
        if fate[0] == "dropped":
            return "dropped"
        return "settled"

    # ------------------------------------------------- the native plane
    def _native_plane(self, native_dir: Path,
                      prefix: str) -> Iterator[Finding]:
        for path in sorted(native_dir.glob("*.h")) + sorted(
                native_dir.glob("*.cc")):
            rel = f"{prefix}{path.name}"
            for fn in native_index.extract_functions(path, rel):
                for ob in native_index.scan_function(fn):
                    if ob.never_settled:
                        yield Finding(
                            ob.rel, ob.line, self.id,
                            f"{ob.label} `{ob.entity}` acquired in "
                            f"{ob.fn_name}() is never released, stored, "
                            "returned, or handed off — leaked "
                            "unconditionally",
                        )
                    elif ob.leak_exit is not None:
                        eline, esrc = ob.leak_exit
                        yield Finding(
                            ob.rel, ob.line, self.id,
                            f"{ob.label} `{ob.entity}` acquired in "
                            f"{ob.fn_name}() leaks at the early exit "
                            f"`{esrc}` (line {eline}) before the "
                            "release — release on the error path or "
                            "adopt it with a scope guard",
                        )
