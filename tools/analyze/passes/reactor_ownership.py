"""reactor-ownership: the single-owner reactor discipline as a rule.

The native serve plane's reactor owns its structures outright: the
epoll interest set, parked-session bookkeeping, ``Session::disp_``
transitions, ``WriteState`` fields, and splice pipe fds are touched by
exactly one thread, so they need no locks — PROVIDED nothing else ever
touches them. Workers hand sessions over through the documented
inbox/eventfd edge (push under the inbox mutex, write the wake
eventfd, reactor drains via ``swap``). That discipline was established
by convention; this rule makes it checkable.

The declared single-owner resource table:

- **epoll set mutations** — every ``epoll_ctl`` call site must be on a
  reactor root.
- **reactor bookkeeping** — writes to ``parked_`` / ``epoll_armed``
  members: reactor root only.
- **inbox members** — members the reactor drains via ``swap``: written
  elsewhere only inside a handoff function (mutation under a lock +
  a wake); any other off-reactor write bypasses the handshake.
- **owned serve state** — ``disp_`` and members of lock-free
  ``*State`` classes (no mutex/atomic/cv member — WriteState,
  TunnelState): written off-reactor only from roots that hold a
  handoff edge (they may prepare a session BEFORE submitting it) or
  in the owning class's own constructor/destructor.

Reads stay silent (the racy-read half is native-guarded-field's
business where locks exist; owned structures are advisory to
observers). Sites no root reaches stay silent — the lifecycle cut
already proves start()/stop() run single-threaded. Trees with no
reactor root (no ``epoll_wait`` under any spawn) are out of scope.
"""

from __future__ import annotations

import re
from typing import Iterator

from tools.analyze.core import Finding, Pass, register
from tools.analyze.native_concurrency import (
    ConcurrencyIndex,
    NativeAnchorMixin,
)

#: member names that are reactor-thread-only bookkeeping wherever they
#: appear in a native tree
REACTOR_ONLY = ("parked_", "epoll_armed")

#: member names that mark owned serve state on any class
OWNED_MEMBERS = ("disp_",)

_EPOLL_CTL_RE = re.compile(r"\bepoll_ctl\s*\(")


@register
class ReactorOwnershipPass(NativeAnchorMixin, Pass):
    id = "reactor-ownership"
    version = "1"
    description = (
        "single-owner reactor discipline over the native serve plane: "
        "epoll set mutations, parked/armed bookkeeping, inbox members "
        "and lock-free *State fields may be written only on the "
        "reactor root or through the documented inbox/eventfd handoff "
        "edge"
    )

    def finalize(self) -> Iterator[Finding]:
        for idx in self.each_index():
            if not idx.reactor_roots:
                continue
            yield from self._check(idx)

    def _check(self, idx: ConcurrencyIndex) -> Iterator[Finding]:
        owner_classes = {
            cls for cls, mems in idx.classes.items()
            if cls.endswith("State") and mems and not any(
                m.kind in ("mutex", "atomic", "cv")
                for m in mems.values())
        }
        handoff_roots: set[str] = set()
        for q in idx.handoff_fns:
            handoff_roots |= idx.roots_of(q)
        seen: set = set()

        def emit(rel, line, what, msg):
            key = (rel, line, what)
            if key in seen:
                return None
            seen.add(key)
            return Finding(rel, line, self.id, msg)

        for q in sorted(idx.functions):
            fn = idx.functions[q]
            roots = idx.roots_of(q)
            off_reactor = sorted(roots - idx.reactor_roots)
            if not off_reactor:
                continue
            r = idx.roots[off_reactor[0]].label

            for st in fn.statements:
                if _EPOLL_CTL_RE.search(st.text):
                    f = emit(fn.rel, st.line, "epoll_ctl",
                             "epoll set mutated here on root "
                             f"'{r}' — the epoll interest set is "
                             "reactor-owned; hand the session to the "
                             "reactor through the inbox/eventfd "
                             "handoff instead")
                    if f:
                        yield f

            for a in fn.accesses:
                if not a.write:
                    continue
                own_ctor = fn.cls == a.cls and \
                    fn.short in (a.cls, f"~{a.cls}")
                if own_ctor:
                    continue
                if a.member in REACTOR_ONLY:
                    f = emit(a.rel, a.line, a.member,
                             f"'{a.cls}::{a.member}' is "
                             "reactor-thread-only bookkeeping but is "
                             f"written here on root '{r}' — only the "
                             "reactor loop may touch it")
                    if f:
                        yield f
                elif (a.cls, a.member) in idx.inbox_members:
                    if q in idx.handoff_fns:
                        continue
                    f = emit(a.rel, a.line, a.member,
                             f"'{a.cls}::{a.member}' is the reactor "
                             "inbox but is written here on root "
                             f"'{r}' outside a handoff function — "
                             "the only legal off-reactor mutation is "
                             "push-under-lock followed by a wake")
                    if f:
                        yield f
                elif a.member in OWNED_MEMBERS or a.cls in owner_classes:
                    if roots & handoff_roots:
                        continue  # may prepare state before submitting
                    f = emit(a.rel, a.line, a.member,
                             f"'{a.cls}::{a.member}' is single-owner "
                             "serve state but is written here on root "
                             f"'{r}', which never hands sessions to "
                             "the reactor — touches must ride the "
                             "inbox/eventfd handoff")
                    if f:
                        yield f
