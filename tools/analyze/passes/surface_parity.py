"""surface-parity: the native and Python planes must resolve one logical
knob/metric/rank identically.

PR 9 found the native proxy resolving ``DEMODEL_TELEMETRY_MIN_MS``
(default 128) while Python resolved ``DEMODEL_TELEMETRY_MIN_GAP_MS``
(default 250) — two surfaces that claim to mirror each other silently
diverging. This pass makes that drift a build-breaking finding, with a
clang-free, regex-level extractor over ``native/*.{h,cc}``:

- **env knobs** — ``env_pos_int("DEMODEL_…")`` / ``getenv("DEMODEL_…")``
  sites plus the ``if (v == 0) v = <literal>;`` fallback idiom yield
  (key, type, default); bool knobs come from the ``if (!v || !*v)
  return true;`` idiom. Python-side: every ``env_int`` / ``env_bool`` /
  ``env_float`` call with a literal ``"DEMODEL_…"`` key in the run.
  Findings: a key BOTH sides resolve with different literal defaults or
  different types; also two PYTHON sites resolving one key with
  different literal defaults (same drift, one plane).
- **metric families** — the keys of the native ``Metrics::json()``
  format string, split into gauges (fields reassigned at scrape time in
  ``Proxy::metrics_json()`` — point-in-time state) and counters, diffed
  against ``utils/metrics.PROXY_GAUGES`` (what ``render`` types the
  scrape with); plus the native-internal check that every
  ``hist_json()`` family is windowed by ``kTelemetryFamilyNames``.
- **lock ranks** — the ``constexpr int kRank… = N;`` table in
  ``native/lock_order.h`` diffed against the Python mirror
  ``demodel_tpu.native.NATIVE_LOCK_RANKS`` (name set and values), plus
  duplicate-rank detection (two locks on one rank defeats the ordering).

Scope/anchoring: the pass activates when the run contains the real
tree's ``demodel_tpu/utils/env.py`` (native dir = ``<root>/native``) or
a file carrying ``# demodel: parity-native=<dir>`` (golden fixtures
point at a miniature fake native tree). Defaults that are not literal
ints/bools on either side ("computed": core-count-derived pool sizes)
are recorded but never compared — no speculative evaluation of C++.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Iterator

from tools.analyze.core import Finding, ModuleContext, Pass, register

_PRAGMA_RE = re.compile(r"#\s*demodel:\s*parity-native=(\S+)")

# ---- native-side extractor patterns ----------------------------------
_RANK_RE = re.compile(r"constexpr\s+int\s+(kRank\w+)\s*=\s*(\d+)\s*;")
_ENV_INT_RE = re.compile(r'env_pos_int\(\s*"(DEMODEL_\w+)"')
_GETENV_RE = re.compile(r'getenv\(\s*"(DEMODEL_\w+)"\s*\)')
_JSON_KEY_RE = re.compile(r'\\"(\w+)\\":%llu')
_GAUGE_ASSIGN_RE = re.compile(r"metrics_\.(\w+)\s*=")
_HIST_FAMILY_RE = re.compile(r'append_hist_family\(\s*&\w+,\s*"(\w+)"')
_TEL_FAMILY_RE = re.compile(
    r"kTelemetryFamilyNames\[\]\s*=\s*\{([^}]*)\}", re.DOTALL)
_STR_RE = re.compile(r'"(\w+)"')

_PY_ENV_FUNCS = {"env_int": "int", "env_bool": "bool", "env_float": "float"}


def _strip_comments(text: str) -> str:
    text = re.sub(r"/\*.*?\*/", " ", text, flags=re.DOTALL)
    return re.sub(r"//[^\n]*", "", text)


def _body_of(text: str, signature_re: str) -> str:
    """Source between a function signature and its column-0 closing
    brace — regex-level scoping, good enough for the two bodies the
    extractor needs."""
    m = re.search(signature_re, text)
    if not m:
        return ""
    end = text.find("\n}", m.end())
    return text[m.end():end if end >= 0 else len(text)]


class NativeSurface:
    """Everything the extractor learned from one native tree."""

    def __init__(self) -> None:
        self.knobs: dict[str, tuple[str, object, str, int]] = {}
        # key → (type, default | "computed", rel, line)
        self.ranks: dict[str, tuple[int, str, int]] = {}
        self.json_keys: list[str] = []
        self.gauge_keys: set[str] = set()
        self.hist_families: set[str] = set()
        self.telemetry_families: set[str] = set()
        self.files_seen = 0


def _line_of(text: str, pos: int) -> int:
    return text.count("\n", 0, pos) + 1


def extract_native(native_dir: Path, rel_prefix: str) -> NativeSurface:
    out = NativeSurface()
    for path in sorted(native_dir.glob("*.h")) + sorted(
            native_dir.glob("*.cc")):
        try:
            raw = path.read_text(encoding="utf-8", errors="replace")
        except OSError:
            continue
        out.files_seen += 1
        rel = f"{rel_prefix}{path.name}"
        text = _strip_comments(raw)

        for m in _RANK_RE.finditer(text):
            out.ranks[m.group(1)] = (int(m.group(2)), rel,
                                     _line_of(text, m.start()))

        # int knobs: env_pos_int("KEY" …) with the `if (v == 0) v = N;`
        # fallback idiom supplying the effective default
        statements = text.split(";")
        for si, stmt in enumerate(statements):
            m = _ENV_INT_RE.search(stmt)
            if not m:
                continue
            key = m.group(1)
            var_m = re.search(r"([A-Za-z_]\w*)\s*=[^=]", stmt)
            default: object = "computed"
            if var_m:
                var = var_m.group(1)
                fallback = re.compile(
                    r"if\s*\(\s*%s\s*(?:==|<=)\s*0\s*\)\s*%s\s*=\s*(.+)"
                    % (re.escape(var), re.escape(var)))
                for nxt in statements[si + 1:si + 6]:
                    fm = fallback.search(nxt)
                    if fm:
                        val = fm.group(1).strip()
                        default = int(val) if re.fullmatch(r"\d+", val) \
                            else "computed"
                        break
            pos = text.find(stmt)
            at = (pos + m.start()) if pos >= 0 else 0
            out.knobs.setdefault(
                key, ("int", default, rel, _line_of(text, at)))

        # bool knobs: getenv("KEY") + `if (!v || !*v) return true;`
        for m in _GETENV_RE.finditer(text):
            key = m.group(1)
            if key in out.knobs:
                continue
            window = text[m.end():m.end() + 400]
            bm = re.search(
                r"if\s*\(\s*!v\s*\|\|\s*!\*v\s*\)\s*return\s+(true|false)",
                window)
            if bm:
                out.knobs[key] = ("bool", bm.group(1) == "true", rel,
                                  _line_of(text, m.start()))
            else:
                out.knobs.setdefault(
                    key, ("str", "computed", rel, _line_of(text, m.start())))

        body = _body_of(text, r"std::string\s+Metrics::json\s*\(")
        if body:
            out.json_keys = _JSON_KEY_RE.findall(body)
        gbody = _body_of(text, r"std::string\s+Proxy::metrics_json\s*\(")
        if gbody:
            fields = set(_GAUGE_ASSIGN_RE.findall(gbody))
            for f in fields:
                for cand in (f, f + "_total"):
                    if cand in out.json_keys:
                        out.gauge_keys.add(cand)
        out.hist_families |= set(_HIST_FAMILY_RE.findall(text))
        tm = _TEL_FAMILY_RE.search(text)
        if tm:
            out.telemetry_families |= set(_STR_RE.findall(tm.group(1)))
    return out


@register
class SurfaceParityPass(Pass):
    id = "surface-parity"
    version = "2"
    description = (
        "native↔Python surface drift: env knobs resolved with different "
        "defaults/types per plane (or twice per plane), native metric "
        "gauge/counter typing disagreeing with utils/metrics.PROXY_GAUGES, "
        "hist families the telemetry window never serves, "
        "native/lock_order.h ranks diverging from the Python mirror, "
        "native mutex members declared without a rank wrapper, and rank "
        "constants no native code ever references (dead rank = drifted "
        "table)"
    )

    @classmethod
    def cache_extra_inputs(cls, files) -> list:
        """The native sources this pass diffs against: their stat
        triples join the per-rule cache key, so a rank/knob edit in
        ``native/*.{h,cc}`` ALONE invalidates this rule's cached
        findings (the analyzed ``.py`` set is unchanged in that case —
        without this, a warm run silently blesses native drift).
        Discovery mirrors the pass's own anchoring: the real tree via
        ``demodel_tpu/utils/env.py`` → ``<root>/native``, fixtures via
        the ``parity-native=`` pragma in the file's head."""
        dirs: list[Path] = []
        for p in files:
            path = Path(p)
            posix = path.as_posix()
            if posix.endswith("demodel_tpu/utils/env.py"):
                root = Path(posix[: -len("demodel_tpu/utils/env.py")]
                            or ".")
                dirs.append(root / "native")
                continue
            try:
                head = path.read_text(encoding="utf-8",
                                      errors="replace")[:4096]
            except OSError:
                continue
            pm = _PRAGMA_RE.search(head)
            if pm:
                dirs.append(path.parent / pm.group(1))
        out: list[Path] = []
        for d in dirs:
            if d.is_dir():
                out.extend(sorted(d.glob("*.h")))
                out.extend(sorted(d.glob("*.cc")))
        return out

    def __init__(self) -> None:
        super().__init__()
        #: key → list of (type, default | "computed", rel, line)
        self._py_knobs: dict[str, list] = {}
        self._proxy_gauges: tuple[set, str, int] | None = None
        self._py_ranks: tuple[dict, str, int] | None = None
        self._native_dirs: list[tuple[Path, str]] = []  # (dir, rel prefix)

    # ------------------------------------------------------------ visit
    def visit(self, ctx: ModuleContext) -> Iterator[Finding]:
        pm = _PRAGMA_RE.search(ctx.source)
        if pm:
            self._native_dirs.append(
                (Path(ctx.path).resolve().parent / pm.group(1),
                 ctx.rel.rsplit("/", 1)[0] + "/" + pm.group(1) + "/"
                 if "/" in ctx.rel else pm.group(1) + "/"))
        elif ctx.rel == "demodel_tpu/utils/env.py":
            # the real tree's anchor: <repo root>/native
            root = Path(str(Path(ctx.path).resolve())[: -len(ctx.rel)]) \
                if str(Path(ctx.path).resolve()).endswith(ctx.rel) \
                else Path.cwd()
            self._native_dirs.append((root / "native", "native/"))

        in_scope = ctx.rel.startswith("demodel_tpu/") or pm is not None
        if not in_scope:
            return iter(())

        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                fname = node.func.attr if isinstance(node.func,
                                                     ast.Attribute) \
                    else (node.func.id if isinstance(node.func, ast.Name)
                          else None)
                if fname in _PY_ENV_FUNCS and node.args \
                        and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str) \
                        and node.args[0].value.startswith("DEMODEL_"):
                    key = node.args[0].value
                    typ = _PY_ENV_FUNCS[fname]
                    default: object = "computed"
                    if len(node.args) > 1:
                        d = node.args[1]
                        if isinstance(d, ast.Constant) and isinstance(
                                d.value, (int, float, bool)):
                            default = d.value
                    elif typ == "bool":
                        default = False  # env_bool's own default
                    self._py_knobs.setdefault(key, []).append(
                        (typ, default, ctx.rel, node.lineno))
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                tgt = node.targets[0].id
                if tgt == "PROXY_GAUGES":
                    names = {
                        e.value for e in ast.walk(node.value)
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)
                    }
                    self._proxy_gauges = (names, ctx.rel, node.lineno)
                elif tgt == "NATIVE_LOCK_RANKS":
                    try:
                        val = ast.literal_eval(node.value)
                    except (ValueError, SyntaxError):
                        val = None
                    if isinstance(val, dict):
                        self._py_ranks = (val, ctx.rel, node.lineno)
        return iter(())

    # --------------------------------------------------------- finalize
    def finalize(self) -> Iterator[Finding]:
        # Python-internal default drift: one key, two literal defaults
        for key, sites in sorted(self._py_knobs.items()):
            lits = [(t, d, rel, line) for t, d, rel, line in sites
                    if d != "computed"]
            seen: dict[object, tuple] = {}
            for t, d, rel, line in lits:
                for prev_d, prev in seen.items():
                    if d != prev_d:
                        yield Finding(
                            rel, line, self.id,
                            f"{key} resolved with default {d!r} here but "
                            f"{prev_d!r} at {prev[2]}:{prev[3]} — one "
                            "logical knob, two Python defaults; move the "
                            "default into a shared resolver",
                        )
                seen.setdefault(d, (t, d, rel, line))

        for native_dir, prefix in self._native_dirs:
            if not native_dir.is_dir():
                continue
            surf = extract_native(native_dir, prefix)
            if not surf.files_seen:
                continue
            yield from self._diff_knobs(surf)
            yield from self._diff_metrics(surf)
            yield from self._diff_ranks(surf)
            yield from self._rank_completeness(native_dir, prefix, surf)

    def _diff_knobs(self, surf: NativeSurface) -> Iterator[Finding]:
        for key, (ntyp, ndef, nrel, nline) in sorted(surf.knobs.items()):
            sites = self._py_knobs.get(key)
            if not sites:
                continue  # native-only knob: nothing claims to mirror it
            for ptyp, pdef, prel, pline in sites:
                if ntyp != "str" and ptyp != ntyp \
                        and {ptyp, ntyp} != {"int", "float"}:
                    yield Finding(
                        prel, pline, self.id,
                        f"{key} is typed {ptyp} here but {ntyp} on the "
                        f"native side ({nrel}:{nline}) — one logical "
                        "knob must parse identically on both planes",
                    )
                    continue
                if pdef == "computed" or ndef == "computed":
                    continue
                if pdef != ndef:
                    yield Finding(
                        prel, pline, self.id,
                        f"{key} defaults to {pdef!r} here but {ndef!r} "
                        f"on the native side ({nrel}:{nline}) — the two "
                        "surfaces mirror each other and must resolve "
                        "one default",
                    )

    def _diff_metrics(self, surf: NativeSurface) -> Iterator[Finding]:
        if self._proxy_gauges is not None and surf.json_keys:
            names, rel, line = self._proxy_gauges
            native_gauges = surf.gauge_keys
            native_keys = set(surf.json_keys)
            for extra in sorted(names - native_gauges):
                why = ("a COUNTER there" if extra in native_keys
                       else "absent from the native scrape")
                yield Finding(
                    rel, line, self.id,
                    f"PROXY_GAUGES names '{extra}' as a native gauge but "
                    f"it is {why} — render() would type the family "
                    "wrong",
                )
            for missing in sorted(native_gauges - names):
                yield Finding(
                    rel, line, self.id,
                    f"native metric '{missing}' is scrape-time pool state "
                    "(a gauge) but PROXY_GAUGES omits it — render() "
                    "types it counter and Prometheus rate() over it "
                    "is garbage",
                )
        if surf.hist_families and surf.telemetry_families:
            for fam in sorted(surf.hist_families
                              - surf.telemetry_families):
                rel, line = self._hist_anchor(surf)
                yield Finding(
                    rel, line, self.id,
                    f"native hist family '{fam}' is exported by "
                    "hist_json() but missing from kTelemetryFamilyNames "
                    "— /debug/telemetry never windows it",
                )

    @staticmethod
    def _hist_anchor(surf: NativeSurface) -> tuple[str, int]:
        # anchor native-internal findings on any rank-bearing file's
        # sibling .cc — fall back to the first knob site
        for key, (_t, _d, rel, line) in surf.knobs.items():
            return rel, line
        return "native", 1

    def _rank_completeness(self, native_dir: Path, prefix: str,
                           surf: NativeSurface) -> Iterator[Finding]:
        """Rank-table completeness, native-internal (needs no Python
        mirror): every mutex member must carry a rank wrapper, and every
        rank constant must be referenced by some native code — a rank
        nothing uses is a hierarchy the table describes but the program
        no longer has."""
        from tools.analyze.native_concurrency import build_index

        idx = build_index(native_dir, prefix)
        if idx is None:
            return
        for cls in sorted(idx.classes):
            for name, mem in sorted(idx.classes[cls].items()):
                if mem.kind == "mutex" and mem.rank is None:
                    yield Finding(
                        mem.rel, mem.line, self.id,
                        f"native mutex member '{cls}::{name}' carries no "
                        "DM_RANKED/kRank wrapper — it is invisible to "
                        "the rank table and to DM_LOCK_ORDER_CHECK",
                    )
        for name, (value, nrel, nline) in sorted(surf.ranks.items()):
            if idx.rank_uses.get(name, 0) == 0:
                yield Finding(
                    nrel, nline, self.id,
                    f"rank constant {name}={value} is never referenced "
                    "by any native mutex or acquisition — dead rank, "
                    "the table has drifted from the code",
                )

    def _diff_ranks(self, surf: NativeSurface) -> Iterator[Finding]:
        if self._py_ranks is None or not surf.ranks:
            return
        mirror, rel, line = self._py_ranks
        by_rank: dict[int, str] = {}
        for name, (value, nrel, nline) in sorted(surf.ranks.items()):
            dup = by_rank.get(value)
            if dup is not None:
                yield Finding(
                    nrel, nline, self.id,
                    f"{name} and {dup} share rank {value} — equal ranks "
                    "defeat the ordered-mutex check (neither can be "
                    "acquired under the other)",
                )
            by_rank[value] = name
            if name not in mirror:
                yield Finding(
                    rel, line, self.id,
                    f"native lock rank {name}={value} ({nrel}:{nline}) "
                    "is missing from NATIVE_LOCK_RANKS — the Python "
                    "mirror no longer describes the real hierarchy",
                )
            elif mirror[name] != value:
                yield Finding(
                    rel, line, self.id,
                    f"NATIVE_LOCK_RANKS[{name!r}] = {mirror[name]} but "
                    f"the native table says {value} ({nrel}:{nline}) — "
                    "rank drift makes the documented hierarchy a lie",
                )
        for name in sorted(set(mirror) - set(surf.ranks)):
            yield Finding(
                rel, line, self.id,
                f"NATIVE_LOCK_RANKS names {name!r} but no such "
                "constexpr rank exists in the native table — stale "
                "mirror entry",
            )
