"""swarm-owner-only-origin: origin chunk fetches outside the scheduler.

The swarm pull's aggregate-origin-bytes ≈ 1× contract holds ONLY because
every origin chunk read goes through :class:`SwarmScheduler`'s ownership
decision (owned → fetch, non-owned → cross-fill or succession). The
origin transport is the module-level ``_swarm_origin_read`` choke in
``demodel_tpu/sink/remote.py`` — a call to it from anywhere outside the
``SwarmScheduler`` class body is an origin fetch that bypassed the
ownership decision, which silently degrades a pod's swarm pull back
toward N× origin traffic.

Scope: files under ``demodel_tpu/sink/`` (where the swarm plane lives)
plus any file carrying an explicit ``# demodel: swarm-plane`` pragma —
which is how the golden fixture opts in, mirroring the wire-policy
pragma convention. Covers the function imported under an alias
(``from ... import _swarm_origin_read as orig``) and module-attribute
calls (``remote._swarm_origin_read(...)``).
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.analyze.core import Finding, ModuleContext, Pass, register

_CHOKE = "_swarm_origin_read"
_OWNER_CLASS = "SwarmScheduler"
_PRAGMA = "# demodel: swarm-plane"


def _enclosing_class(node: ast.AST) -> str | None:
    cur = getattr(node, "_dm_parent", None)
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return cur.name
        cur = getattr(cur, "_dm_parent", None)
    return None


@register
class SwarmOriginPolicyPass(Pass):
    id = "swarm-owner-only-origin"
    description = (
        "origin chunk fetch (_swarm_origin_read) outside SwarmScheduler "
        "in sink/ — every swarm origin byte must route through the "
        "scheduler's ownership decision or the aggregate-origin ≈ 1x "
        "contract silently degrades to per-host origin pulls"
    )

    def visit(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not (ctx.rel.startswith("demodel_tpu/sink/")
                or _PRAGMA in ctx.source):
            return
        aliases: set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom):
                for a in node.names:
                    if a.name == _CHOKE:
                        aliases.add(a.asname or a.name)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            named = (
                (isinstance(fn, ast.Name)
                 and (fn.id == _CHOKE or fn.id in aliases))
                or (isinstance(fn, ast.Attribute) and fn.attr == _CHOKE)
            )
            if not named:
                continue
            if _enclosing_class(node) == _OWNER_CLASS:
                continue
            yield Finding(
                ctx.rel, node.lineno, self.id,
                f"{_CHOKE}() called outside SwarmScheduler — an origin "
                "chunk fetch that bypasses the ownership decision "
                "degrades the swarm's aggregate-origin-bytes contract; "
                "route it through the scheduler (ensure/_fetch_origin)",
            )
