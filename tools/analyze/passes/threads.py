"""unjoined-thread: flag ``threading.Thread`` objects that are started but
neither joined, tracked, nor daemonized.

First of ROADMAP's "async-cancellation safety" rules: a fire-and-forget
thread outlives the error path that spawned it — ``stop()``/teardown can't
drain it, sanitizers can't see past its detach, and under load it is the
thread-bomb shape the serve plane's bounded executor exists to prevent.

A started thread is considered OWNED (no finding) when, in the same scope,
it is any of:

- constructed with ``daemon=True`` (the runtime reaps it at exit);
- ``.join()``-ed, or has ``.daemon`` assigned before start;
- stored: assigned to an attribute (``self._worker = t``), passed to a
  call (``threads.append(t)``, ``registry.track(t)``), placed in a
  list/tuple/dict/set literal or comprehension, returned, or yielded —
  ownership moved somewhere that can join it later.

Deliberate fire-and-forget (rare, justified) gets an inline
``# demodel: allow(unjoined-thread)`` with a why.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.analyze.core import (
    Finding,
    ModuleContext,
    Pass,
    dotted,
    enclosing_function,
    register,
    walk_in_scope,
)


def _is_thread_ctor(node: ast.Call) -> bool:
    name = dotted(node.func)
    return name is not None and (name == "Thread" or name.endswith(".Thread"))


def _has_daemon_true(node: ast.Call) -> bool:
    for kw in node.keywords:
        if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
            return bool(kw.value.value)
    return False


def _scope_of(node: ast.AST, ctx: ModuleContext) -> ast.AST:
    fn = enclosing_function(node)
    return fn if fn is not None else ctx.tree


def _name_events(scope: ast.AST, name: str) -> dict[str, bool]:
    """How a local thread variable is used inside ``scope``."""
    ev = {"started": False, "owned": False}
    for sub in walk_in_scope(scope):
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute):
            recv = sub.func.value
            if isinstance(recv, ast.Name) and recv.id == name:
                if sub.func.attr == "start":
                    ev["started"] = True
                if sub.func.attr == "join":
                    ev["owned"] = True
        if isinstance(sub, ast.Call):
            # passed somewhere (threads.append(t), pool.track(t), ...):
            # ownership handed off
            for arg in list(sub.args) + [kw.value for kw in sub.keywords]:
                if isinstance(arg, ast.Name) and arg.id == name:
                    ev["owned"] = True
        if isinstance(sub, ast.Assign):
            # self._worker = t / registry["x"] = t → tracked;
            # t.daemon = True → reaped at exit
            for tgt in sub.targets:
                if (isinstance(tgt, (ast.Attribute, ast.Subscript))
                        and isinstance(sub.value, ast.Name)
                        and sub.value.id == name):
                    ev["owned"] = True
                if (isinstance(tgt, ast.Attribute) and tgt.attr == "daemon"
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == name):
                    ev["owned"] = True
        if isinstance(sub, (ast.Return, ast.Yield, ast.YieldFrom)):
            val = sub.value
            if isinstance(val, ast.Name) and val.id == name:
                ev["owned"] = True
            if isinstance(val, (ast.Tuple, ast.List)):
                for elt in val.elts:
                    if isinstance(elt, ast.Name) and elt.id == name:
                        ev["owned"] = True
    return ev


@register
class UnjoinedThreadPass(Pass):
    id = "unjoined-thread"
    description = (
        "threading.Thread started but never joined, tracked, or daemonized "
        "(orphaned on error paths; unbounded under load)"
    )

    def visit(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not _is_thread_ctor(node):
                continue
            if _has_daemon_true(node):
                continue
            parent = getattr(node, "_dm_parent", None)
            # Thread(...).start() — fire-and-forget, nothing ever owns it
            if (isinstance(parent, ast.Attribute) and parent.attr == "start"
                    and isinstance(getattr(parent, "_dm_parent", None),
                                   ast.Call)):
                yield Finding(
                    ctx.rel, node.lineno, self.id,
                    "Thread(...).start() without join/daemon/tracking — "
                    "orphaned on error paths",
                )
                continue
            # t = Thread(...): require join/track/daemon for a started t
            if isinstance(parent, ast.Assign):
                tgts = parent.targets
                if len(tgts) == 1 and isinstance(tgts[0], ast.Name):
                    ev = _name_events(_scope_of(node, ctx), tgts[0].id)
                    if ev["started"] and not ev["owned"]:
                        yield Finding(
                            ctx.rel, node.lineno, self.id,
                            f"thread '{tgts[0].id}' is start()ed but never "
                            "joined, tracked, or daemonized",
                        )
                # assignment to an attribute/subscript target is tracking
            # any other context (call argument, collection literal,
            # comprehension, return) moves ownership — no finding
