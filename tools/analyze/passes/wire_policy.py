"""wire-call-policy: direct ``requests`` verb calls outside the faults layer.

Every HTTP call on the pull/restore/registry plane must route through
``demodel_tpu/utils/faults.py`` (``RetryPolicy`` + ``PeerHealth`` +
``request_with_retry``): a direct ``requests.get/post/head`` is a
single-attempt, breaker-blind call — exactly the shape the wire-plane
fault-tolerance work removed. The rule covers the module imported under
any alias (``import requests as rq``) and verbs pulled in directly
(``from requests import get``).

Scope: files under ``demodel_tpu/`` (minus the faults module itself) plus
any file carrying an explicit ``# demodel: wire-plane`` pragma — which is
how the golden fixture opts in, mirroring the host-sync ``hot-path``
pragma.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.analyze.core import Finding, ModuleContext, Pass, register

#: HTTP-issuing callables on the requests module / top-level API
_VERBS = {"get", "post", "head", "put", "delete", "patch", "options",
          "request"}

_EXEMPT = "demodel_tpu/utils/faults.py"
_PRAGMA = "# demodel: wire-plane"


@register
class WireCallPolicyPass(Pass):
    id = "wire-call-policy"
    description = (
        "direct requests.get/post/head(...) in demodel_tpu/ outside "
        "utils/faults.py — wire calls must ride the RetryPolicy/"
        "PeerHealth layer (demodel_tpu.utils.faults)"
    )

    def visit(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.rel == _EXEMPT:
            return
        if not (ctx.rel.startswith("demodel_tpu/")
                or _PRAGMA in ctx.source):
            return
        module_aliases: set[str] = set()
        verb_names: dict[str, str] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "requests":
                        module_aliases.add(a.asname or "requests")
            elif isinstance(node, ast.ImportFrom) and node.level == 0 \
                    and node.module == "requests":
                for a in node.names:
                    if a.name in _VERBS:
                        verb_names[a.asname or a.name] = a.name
        if not module_aliases and not verb_names:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            verb = None
            if (isinstance(fn, ast.Attribute) and fn.attr in _VERBS
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id in module_aliases):
                verb = fn.attr
            elif isinstance(fn, ast.Name) and fn.id in verb_names:
                verb = verb_names[fn.id]
            if verb is not None:
                yield Finding(
                    ctx.rel, node.lineno, self.id,
                    f"direct requests.{verb}() is single-attempt and "
                    "breaker-blind — route it through "
                    "demodel_tpu.utils.faults (request_with_retry / "
                    "RetryPolicy)",
                )
