"""SARIF 2.1.0 writer — the interchange format CI uses to annotate PRs
(``github/codeql-action/upload-sarif`` renders each result as an inline
review comment at its file:line)."""

from __future__ import annotations

SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
          "Schemas/sarif-schema-2.1.0.json")


def to_sarif(active, suppressed, registry) -> dict:
    """One SARIF run over both finding sets; suppressed findings carry a
    ``suppressions`` entry so viewers show them struck-through instead of
    hiding that they exist."""
    rule_ids = sorted({f.rule for f in active}
                      | {f.rule for f in suppressed}
                      | set(registry))
    rules = [
        {
            "id": rid,
            "shortDescription": {
                "text": getattr(registry.get(rid), "description", "") or rid,
            },
        }
        for rid in rule_ids
    ]
    index = {rid: i for i, rid in enumerate(rule_ids)}

    def result(f, suppressed_flag: bool) -> dict:
        out = {
            "ruleId": f.rule,
            "ruleIndex": index[f.rule],
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.path,
                        "uriBaseId": "SRCROOT",
                    },
                    "region": {"startLine": f.line},
                },
            }],
        }
        if suppressed_flag:
            out["suppressions"] = [{
                "kind": "inSource",
                "justification": "inline `# demodel: allow(...)`",
            }]
        return out

    return {
        "$schema": SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {
                "driver": {
                    "name": "demodel-analyze",
                    "informationUri":
                        "https://example.invalid/tools/analyze",
                    "rules": rules,
                },
            },
            "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
            "results": [result(f, False) for f in active]
            + [result(f, True) for f in suppressed],
        }],
    }
