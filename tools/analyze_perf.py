#!/usr/bin/env python3
"""CI analyze-perf leg: profile a cold full-tree analyze, gate the warm one.

Two contracts, one script (the CI ``analyze-perf`` step runs it):

- the COLD run executes in-process under the repo's own sampling
  profiler (``demodel_tpu.utils.profiler``, the PR 13 plane) and writes
  a collapsed flame (``analyze_cold.folded``, the flamegraph.pl /
  speedscope interchange) uploaded as a build artifact — an analyzer
  slowdown is diagnosable from the CI page without reproducing locally;
- the WARM run (result cache hot) goes through the real CLI twice —
  prime, then measure — and must report ``cache: hit`` with ``secs:``
  under the budget (default 0.5s, ``DEMODEL_ANALYZE_WARM_BUDGET``
  overrides). The same bound is a tier-1 test
  (``test_warm_cache_is_subsecond``); this leg catches the regression
  on the PR that introduces it even when the test suite is skipped.

Usage: ``python tools/analyze_perf.py [paths...]`` (default demodel_tpu).
"""

from __future__ import annotations

import os
import re
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]


def main(argv: list[str] | None = None) -> int:
    sys.path.insert(0, str(REPO))
    os.chdir(REPO)
    from demodel_tpu.utils.profiler import Profiler, collapse
    from tools.analyze.__main__ import main as analyze_main

    paths = list(argv if argv is not None else sys.argv[1:]) or ["demodel_tpu"]

    # cold leg: private Profiler instance (no DEMODEL_OBS gating, no
    # singleton) sampling the analyzing thread at a rate high enough to
    # resolve per-pass frames on a runs-in-seconds workload
    prof = Profiler(hz=250, max_stacks=4096)
    prof.start()
    try:
        rc_cold = analyze_main(["--no-cache", "--stats", *paths])
    finally:
        prof.stop()
    snap = prof.snapshot()
    record = {"stacks": [
        {"stack": k, "wall": v[0], "cpu": v[1]} for k, v in snap.items()]}
    flame = REPO / "analyze_cold.folded"
    flame.write_text(collapse(record))
    print(f"cold analyze rc={rc_cold}; "
          f"{sum(v[0] for v in snap.values())} wall samples -> {flame}",
          file=sys.stderr)

    # the native call-graph leg: the cold run above must have BUILT the
    # clang-free C++ index (classes/functions/roots over native/) — if
    # the three native rules silently stopped anchoring, the warm gate
    # below would still pass on an empty workload, so check the index
    # cache the in-process run populated before trusting the timing
    from tools.analyze import REGISTRY
    from tools.analyze import native_concurrency as nc
    native_rules = {"native-guarded-field", "native-lock-order",
                    "reactor-ownership"}
    missing = native_rules - set(REGISTRY)
    if missing:
        print(f"::error::native rules absent from registry: "
              f"{sorted(missing)}", file=sys.stderr)
        return 1
    built = [idx for idx in nc._INDEX_CACHE.values() if idx is not None]
    if not built or not any(idx.functions for idx in built):
        print("::error::cold analyze never built the native call-graph "
              "index — the concurrency rules are not anchoring",
              file=sys.stderr)
        return 1
    fns = sum(len(idx.functions) for idx in built)
    roots = sum(len(idx.roots) for idx in built)
    print(f"native index: {len(built)} tree(s), {fns} function(s), "
          f"{roots} thread root(s)", file=sys.stderr)

    # warm leg: prime, then measure through the real CLI so the gate
    # covers key computation + cache load, not just the passes
    budget = float(os.environ.get("DEMODEL_ANALYZE_WARM_BUDGET", "0.5"))
    cmd = [sys.executable, "-m", "tools.analyze", "--stats", *paths]
    subprocess.run(cmd, cwd=REPO, capture_output=True, text=True)
    warm = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True)
    m = re.search(r"secs: ([0-9.]+)", warm.stderr)
    if not m or "cache: hit" not in warm.stderr:
        print("warm leg did not report a cache hit:\n" + warm.stderr,
              file=sys.stderr)
        return 1
    secs = float(m.group(1))
    print(f"warm analyze: {secs:.3f}s (budget {budget}s, cache hit)",
          file=sys.stderr)
    if secs >= budget:
        print(f"::error::warm analyze took {secs:.3f}s >= {budget}s — "
              "the result cache regressed", file=sys.stderr)
        return 1
    return rc_cold


if __name__ == "__main__":
    sys.exit(main())
