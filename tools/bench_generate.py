"""Token-serving benchmark driver — prints ONE JSON line (same contract
as ``bench.py``/``bench_serve.py``/``bench_store.py``; those time MB/s
planes, this one gives the suite its tokens/s axis).

Scenario legs:

  prefill   tokens/s through ``serve.prefill`` (requests sized so the
            prompt dominates: max_new=1).
  decode    steady-state decode tokens/s with the continuous batch full.
  batching  the tentpole contract: the SAME requests served (a) all
            admitted up front (continuous batching interleaves them) vs
            (b) strictly one-at-a-time; the rc gate holds the continuous
            leg at ≥ 1.5× the sequential tokens/s.
  overflow  a thundering herd against a 1-wide engine with a tiny
            waiting room, through the REAL ``/generate`` HTTP surface:
            every request must answer 200 or 503+Retry-After — the
            zero-silent-drops admission contract — and the KV pool must
            account back to zero after the run.

Env knobs: DEMODEL_GENBENCH_REQS (16), DEMODEL_GENBENCH_PROMPT (32),
DEMODEL_GENBENCH_NEW (48), DEMODEL_GENBENCH_BATCH (8). ``--smoke`` (or
DEMODEL_GENBENCH_SMOKE=1) shrinks everything for CI; the rc gates
(batching ratio, overflow accounting, KV leak) hold at every size.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _env_i(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    try:
        return int(raw) if raw else default
    except ValueError:
        return default


SMOKE = ("--smoke" in sys.argv
         or os.environ.get("DEMODEL_GENBENCH_SMOKE", "").strip() == "1")
N_REQS = _env_i("DEMODEL_GENBENCH_REQS", 4 if SMOKE else 16)
PROMPT_LEN = _env_i("DEMODEL_GENBENCH_PROMPT", 8 if SMOKE else 32)
MAX_NEW = _env_i("DEMODEL_GENBENCH_NEW", 8 if SMOKE else 48)
MAX_BATCH = _env_i("DEMODEL_GENBENCH_BATCH", 4 if SMOKE else 8)


def _build():
    import jax

    from demodel_tpu.models import llama

    if SMOKE:
        cfg = llama.LlamaConfig.tiny()
    else:
        cfg = llama.LlamaConfig(
            vocab_size=512, hidden_size=128, intermediate_size=256,
            num_hidden_layers=4, num_attention_heads=8,
            num_key_value_heads=4)
    params = llama.init_params(jax.random.key(7), cfg)
    return params, cfg


def _prompts(cfg, n: int) -> list[list[int]]:
    return [[(7 * i + 3 * j + 1) % cfg.vocab_size
             for j in range(PROMPT_LEN)] for i in range(n)]


def _drain(engine, prompts, max_new: int) -> tuple[float, int]:
    """Submit everything up front, wait for all; (wall_s, tokens)."""
    t0 = time.perf_counter()
    reqs = [engine.submit(p, max_new) for p in prompts]
    toks = sum(len(r.result(timeout=600)) for r in reqs)
    return time.perf_counter() - t0, toks


def _sequential(engine, prompts, max_new: int) -> tuple[float, int]:
    """One request at a time — the no-batching reference serving mode."""
    t0 = time.perf_counter()
    toks = 0
    for p in prompts:
        toks += len(engine.submit(p, max_new).result(timeout=600))
    return time.perf_counter() - t0, toks


def _throughput_legs(params, cfg) -> dict:
    from demodel_tpu import serve

    engine = serve.GenEngine(params, cfg, max_batch=MAX_BATCH,
                             queue_limit=max(64, 4 * N_REQS),
                             max_new_tokens=max(MAX_NEW, 8),
                             kv_mb=64).start()
    try:
        prompts = _prompts(cfg, N_REQS)
        # warm the jit caches (prefill shape + decode buckets) so the
        # measured legs time serving, not XLA compilation
        _drain(engine, prompts[:MAX_BATCH], 2)
        _sequential(engine, prompts[:1], 2)

        pre_s, _ = _drain(engine, prompts, 1)
        prefill_tok_s = N_REQS * PROMPT_LEN / pre_s if pre_s else 0.0

        cont_s, cont_toks = _drain(engine, prompts, MAX_NEW)
        seq_s, seq_toks = _sequential(engine, prompts, MAX_NEW)
        cont_tok_s = cont_toks / cont_s if cont_s else 0.0
        seq_tok_s = seq_toks / seq_s if seq_s else 0.0
        ratio = cont_tok_s / seq_tok_s if seq_tok_s else 0.0
        kv_after = engine.pool.describe()
    finally:
        engine.stop()
    return {
        "requests": N_REQS, "prompt_len": PROMPT_LEN, "max_new": MAX_NEW,
        "max_batch": MAX_BATCH,
        "prefill_tok_s": round(prefill_tok_s, 2),
        "decode_tok_s": round(cont_tok_s, 2),
        "continuous_s": round(cont_s, 3),
        "sequential_s": round(seq_s, 3),
        "continuous_tok_s": round(cont_tok_s, 2),
        "sequential_tok_s": round(seq_tok_s, 2),
        "batching_ratio": round(ratio, 3),
        "batching_ok": bool(ratio >= 1.5),
        "kv_blocks_in_use_after": kv_after["in_use_blocks"],
        "kv_budget_in_use_after": kv_after["budget"]["in_use_bytes"],
    }


def _overflow_leg(params, cfg, tmp: Path) -> dict:
    """The admission contract through the real HTTP surface."""
    from demodel_tpu import serve
    from demodel_tpu.restore.server import RestoreRegistry, RestoreServer
    from demodel_tpu.store import Store

    engine = serve.GenEngine(params, cfg, max_batch=1, queue_limit=2,
                             max_new_tokens=max(MAX_NEW, 8),
                             kv_mb=16).start()
    serve.install(engine)
    store = Store(tmp / "store")
    server = RestoreServer(RestoreRegistry(store), host="127.0.0.1").start()
    url = f"http://127.0.0.1:{server.port}/generate"
    n = max(8, 2 * N_REQS)
    prompts = _prompts(cfg, n)
    results: list[dict] = [None] * n  # type: ignore[list-item]

    def _one(i: int) -> None:
        body = json.dumps({"prompt": prompts[i],
                           "max_new_tokens": MAX_NEW}).encode()
        req = urllib.request.Request(url, data=body, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=600) as resp:
                doc = json.loads(resp.read())
                results[i] = {"status": 200,
                              "tokens": len(doc.get("tokens", []))}
        except urllib.error.HTTPError as e:
            results[i] = {"status": e.code,
                          "retry_after": e.headers.get("Retry-After")}
            e.read()
        except Exception as e:  # noqa: BLE001 — a drop must be visible
            results[i] = {"status": -1, "error": str(e)}

    threads = [threading.Thread(target=_one, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=900)
    served = [r for r in results if r and r["status"] == 200]
    rejected = [r for r in results if r and r["status"] == 503]
    other = [r for r in results
             if r is None or r["status"] not in (200, 503)]
    retry_after_ok = all(r.get("retry_after") not in (None, "")
                         for r in rejected)
    tokens_ok = all(r["tokens"] == MAX_NEW for r in served)
    server.stop()
    engine.stop()
    serve.install(None)
    store.close()
    kv_after = engine.pool.describe()
    return {
        "requests": n,
        "served_200": len(served),
        "rejected_503": len(rejected),
        "silent_drops": len(other),
        "retry_after_on_every_503": retry_after_ok,
        "served_complete": tokens_ok,
        "kv_blocks_in_use_after": kv_after["in_use_blocks"],
        "overflow_ok": bool(
            len(other) == 0 and len(rejected) > 0 and retry_after_ok
            and tokens_ok and kv_after["in_use_blocks"] == 0),
    }


def main() -> int:
    params, cfg = _build()
    legs = _throughput_legs(params, cfg)
    with tempfile.TemporaryDirectory() as td:
        overflow = _overflow_leg(params, cfg, Path(td))

    kv_ok = (legs.pop("kv_blocks_in_use_after") == 0
             and legs.pop("kv_budget_in_use_after") == 0
             and overflow["kv_blocks_in_use_after"] == 0)
    result = {
        "metric": "gen_decode_tokens_per_s",
        "value": legs["decode_tok_s"],
        "unit": "tokens/s",
        "vs_baseline": 0.0,  # first tokens/s datapoint — no prior anchor
        "smoke": SMOKE,
        "model": {
            "layers": cfg.num_hidden_layers, "hidden": cfg.hidden_size,
            "heads": cfg.num_attention_heads,
            "kv_heads": cfg.num_key_value_heads,
            "vocab": cfg.vocab_size},
        "serving": legs,
        "overflow": overflow,
        "kv_accounting_zero": kv_ok,
    }
    print(json.dumps(result))
    if not legs["batching_ok"]:
        print("[bench_generate] BATCHING CONTRACT VIOLATED "
              f"(ratio {legs['batching_ratio']} < 1.5)", file=sys.stderr)
        return 1
    if not overflow["overflow_ok"]:
        print("[bench_generate] OVERFLOW CONTRACT VIOLATED", file=sys.stderr)
        return 1
    if not kv_ok:
        print("[bench_generate] KV ACCOUNTING LEAK", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
