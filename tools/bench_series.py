"""Run `bench.py` N times and record every parsed line — the
measurement protocol for when the axon tunnel recovers (PROFILE_r04.md):
multiple reps, committed, so the driver-comparable number is a
distribution rather than one lucky/unlucky sample.

Usage: python tools/bench_series.py [reps] [outfile]
Appends one JSON object per rep to BENCH_SERIES_r05.jsonl and prints a
min/median/max summary.
"""

from __future__ import annotations

import datetime
import json
import statistics
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def main() -> int:
    reps = int(sys.argv[1]) if len(sys.argv) > 1 else 3
    out_path = Path(sys.argv[2]) if len(sys.argv) > 2 else \
        REPO / "BENCH_SERIES_r05.jsonl"
    values = []
    for i in range(reps):
        proc = subprocess.run([sys.executable, str(REPO / "bench.py")],
                              capture_output=True, text=True, timeout=1800)
        parsed = None
        for line in reversed(proc.stdout.strip().splitlines()):
            try:
                parsed = json.loads(line)
                break
            except ValueError:
                continue
        rec = {
            "ts": datetime.datetime.now(
                datetime.timezone.utc).isoformat(timespec="seconds"),
            "rep": i,
            "parsed": parsed,
            "stderr_tail": proc.stderr[-1200:],
        }
        with open(out_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        print(json.dumps(parsed))
        if parsed and parsed.get("metric") == "cold_pull_to_hbm_throughput":
            values.append(float(parsed["value"]))
    if values:
        print(f"[series] n={len(values)} min={min(values):.1f} "
              f"median={statistics.median(values):.1f} "
              f"max={max(values):.1f} MB/s/chip", file=sys.stderr)
    else:
        print("[series] no e2e results (tunnel still down?)",
              file=sys.stderr)
    return 0 if values else 1


if __name__ == "__main__":
    sys.exit(main())
