"""Serve-plane benchmark driver — prints ONE JSON line (same contract as
the delivery-side ``bench.py``; that driver times cold-pull→HBM, this one
times the OTHER half of the system: re-serving cached blobs to many
clients, the reference's whole value proposition).

Scenario: a loopback proxy node over a warmed content-addressed store,
``DEMODEL_SERVE_CLIENTS`` concurrent keep-alive clients hammering the
hot-hit endpoints —

  object   ``GET /peer/object/{key}`` full-body hits (the sendfile path);
           the headline metric is this leg's MB/s;
  meta     ``GET /peer/meta/{key}`` small-JSON hits;
  index    ``GET /peer/index`` generation-cached store index.

Each leg reports reqs/s and p50/p99 latency; the object leg adds MB/s.

A separate **flood leg** restarts the proxy with ``DEMODEL_PROXY_THREADS=4``
and opens connections ≫ pool+queue, asserting the bounded-session-executor
contract: process thread count stays at pool + constant, overflow is
answered ``503 + Retry-After`` (never silently dropped), and every
connection gets a response. On a pre-pool (detach-per-connection) build the
flood leg still runs but only reports — ``flood_ok`` is null there.

The **C10k leg** drives the event-driven serve plane: thousands of
concurrent keep-alive connections against a small pool. Every connection
is served once and then parks in the epoll reactor; the leg asserts zero
silent drops, the ``sessions_parked`` gauge tracking the conn count, a
CPU-time bound while the horde idles (parked conns must cost no poll
cycles), hot-hit throughput unaffected by the parked horde, parked conns
resuming on their next request, and the 503+Retry-After admission contract
past ``DEMODEL_PROXY_MAX_CONNS``. On a reactor-less build it only reports
(``c10k_ok`` null).

The **C100k leg** drives the EPOLLOUT writer plane: a slow-reader horde
(~10 KB/s drains, run in a child process so its fds and GIL don't contend
with the measured clients) requests a multi-MB object each and trickles it
out, so every response is writer-plane-owned for the whole leg; a
fast-client throughput leg through the same small pool proves writers hold
zero workers; reactor-spliced CONNECT tunnels idle alongside (a byte
echoed both ways each); admission past ``max_conns`` still answers
503+Retry-After; and a stall sub-leg with ``DEMODEL_PROXY_WRITE_TIMEOUT=2``
proves trickle clients are evicted and counted. On a pre-writer build it
only reports (``c100k_ok`` null).

Env knobs: DEMODEL_SERVE_OBJ_MB (default 8), DEMODEL_SERVE_OBJECTS (4),
DEMODEL_SERVE_CLIENTS (8), DEMODEL_SERVE_SECS (3.0), DEMODEL_SERVE_FLOOD
(200), DEMODEL_SERVE_C10K (2500 conns), DEMODEL_SERVE_C10K_POOL (8),
DEMODEL_SERVE_HORDE (10000 slow readers), DEMODEL_SERVE_HORDE_POOL (8),
DEMODEL_SERVE_TUNNELS (32), DEMODEL_SERVE_FAST_P99_SLO_MS (500).
``--smoke`` (or DEMODEL_SERVE_SMOKE=1) shrinks everything for CI — except
the C10k leg, which stays at 1000 conns on a 2-worker pool so the smoke
still proves the reactor contract at meaningful scale; the C100k smoke
runs 200 slow readers on a 2-worker pool.
"""

from __future__ import annotations

import http.client
import json
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def _env_f(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


SMOKE = ("--smoke" in sys.argv
         or os.environ.get("DEMODEL_SERVE_SMOKE", "").strip() == "1")
PROFILE = ("--profile" in sys.argv
           or os.environ.get("DEMODEL_SERVE_PROFILE", "").strip() == "1")
OBJ_MB = int(_env_f("DEMODEL_SERVE_OBJ_MB", 1 if SMOKE else 8))
N_OBJECTS = int(_env_f("DEMODEL_SERVE_OBJECTS", 2 if SMOKE else 4))
N_CLIENTS = int(_env_f("DEMODEL_SERVE_CLIENTS", 4 if SMOKE else 8))
LEG_SECS = _env_f("DEMODEL_SERVE_SECS", 1.0 if SMOKE else 3.0)
FLOOD_CONNS = int(_env_f("DEMODEL_SERVE_FLOOD", 48 if SMOKE else 200))
FLOOD_THREADS = 4  # the acceptance-criteria pool size
C10K_CONNS = int(_env_f("DEMODEL_SERVE_C10K", 1000 if SMOKE else 2500))
C10K_POOL = int(_env_f("DEMODEL_SERVE_C10K_POOL", 2 if SMOKE else 8))
HORDE_CONNS = int(_env_f("DEMODEL_SERVE_HORDE", 200 if SMOKE else 10000))
HORDE_POOL = int(_env_f("DEMODEL_SERVE_HORDE_POOL", 2 if SMOKE else 8))
HORDE_TUNNELS = int(_env_f("DEMODEL_SERVE_TUNNELS", 8 if SMOKE else 32))
FAST_P99_SLO_MS = _env_f("DEMODEL_SERVE_FAST_P99_SLO_MS", 500.0)


def _proc_threads() -> int:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("Threads:"):
                return int(line.split()[1])
    return -1


def _percentile(sorted_vals: list[float], pct: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(pct / 100 * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def _node(tmp: Path):
    from demodel_tpu.config import ProxyConfig
    from demodel_tpu.proxy import ProxyServer

    cfg = ProxyConfig(
        host="127.0.0.1", port=0, mitm_hosts=[], no_mitm=True,
        cache_dir=tmp / "cache", data_dir=tmp / "data", use_ecdsa=True,
    )
    return ProxyServer(cfg, verbose=False)


def _warm_store(cache_dir: Path, n: int, mb: int) -> list[str]:
    """Put n objects of mb MB each straight into the node's store root."""
    from demodel_tpu.store import Store

    keys = []
    s = Store(cache_dir / "proxy")
    try:
        body = os.urandom(1 << 20) * mb  # mb MB, incompressible enough
        for i in range(n):
            key = f"servebench{i:06d}"
            s.put(key, body, {"content-type": "application/octet-stream"})
            keys.append(key)
    finally:
        s.close()
    return keys


def _hammer(port: int, path_for, secs: float, clients: int,
            expect_body: bool) -> tuple[int, int, list[float]]:
    """``clients`` keep-alive connections looping GETs for ``secs``.

    Returns (requests_completed, bytes_received, latencies_sec)."""
    stop = time.perf_counter() + secs
    lock = threading.Lock()
    total_reqs = 0
    total_bytes = 0
    lats: list[float] = []
    errors: list[BaseException] = []  # re-raised in main: a worker dying
    # silently would deflate reqs/s and still exit 0 (the CI smoke's only
    # guard is value>0, so swallowed failures must surface here)

    def worker(wid: int) -> None:
        nonlocal total_reqs, total_bytes
        reqs = 0
        nbytes = 0
        mine: list[float] = []
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        try:
            i = 0
            while time.perf_counter() < stop:
                t0 = time.perf_counter()
                conn.request("GET", path_for(wid, i))
                resp = conn.getresponse()
                body = resp.read()
                mine.append(time.perf_counter() - t0)
                if resp.status != 200:
                    raise AssertionError(
                        f"hot hit returned {resp.status} on {path_for(wid, i)}")
                if expect_body and not body:
                    raise AssertionError("empty hot-hit body")
                reqs += 1
                nbytes += len(body)
                i += 1
        except BaseException as e:  # noqa: BLE001 — reported by the caller
            with lock:
                errors.append(e)
        finally:
            conn.close()
            with lock:
                total_reqs += reqs
                total_bytes += nbytes
                lats.extend(mine)

    threads = [threading.Thread(target=worker, args=(w,)) for w in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]
    return total_reqs, total_bytes, sorted(lats)


def _leg(name: str, port: int, path_for, secs: float, clients: int,
         expect_body: bool) -> dict:
    reqs, nbytes, lats = _hammer(port, path_for, secs, clients, expect_body)
    out = {
        f"{name}_reqs_s": round(reqs / secs, 1),
        f"{name}_p50_ms": round(_percentile(lats, 50) * 1e3, 3),
        f"{name}_p99_ms": round(_percentile(lats, 99) * 1e3, 3),
    }
    if expect_body:
        out[f"{name}_mb_s"] = round(nbytes / 1e6 / secs, 2)
    print(f"[bench_serve] {name}: {reqs} reqs in {secs:.1f}s "
          f"({out[f'{name}_reqs_s']}/s, p50={out[f'{name}_p50_ms']}ms, "
          f"p99={out[f'{name}_p99_ms']}ms)", file=sys.stderr)
    return out


def _flood(tmp: Path) -> dict:
    """Connections ≫ pool: every one must get a 200 or a 503+Retry-After,
    and the process must not grow a thread per connection."""
    key = _warm_store(tmp / "flood-node" / "cache", 1, 1)[0]
    os.environ["DEMODEL_PROXY_THREADS"] = str(FLOOD_THREADS)
    try:
        node = _node(tmp / "flood-node").start()
    finally:
        del os.environ["DEMODEL_PROXY_THREADS"]
    try:
        # the pool exists iff the native metrics carry the serve counters
        pooled = "sessions_rejected_total" in node.metrics()
        base_threads = _proc_threads()
        peak = {"threads": base_threads}
        results = []  # per-connection: ("200"|"503"|"err", retry_after_seen)
        rlock = threading.Lock()
        start_gate = threading.Barrier(FLOOD_CONNS + 1)

        def one_conn() -> None:
            outcome, retry_after = "err", False
            try:
                start_gate.wait(timeout=60)
                conn = http.client.HTTPConnection("127.0.0.1", node.port,
                                                  timeout=60)
                try:
                    conn.request("GET", f"/peer/object/{key}",
                                 headers={"Connection": "close"})
                    resp = conn.getresponse()
                    resp.read()
                    outcome = str(resp.status)
                    retry_after = resp.getheader("Retry-After") is not None
                finally:
                    conn.close()
            except Exception as e:  # noqa: BLE001 — recorded as a drop
                outcome = f"err:{type(e).__name__}"
            with rlock:
                results.append((outcome, retry_after))

        threads = [threading.Thread(target=one_conn)
                   for _ in range(FLOOD_CONNS)]
        for t in threads:
            t.start()
        start_gate.wait(timeout=60)  # release the whole burst at once
        # sample thread count while the burst is in flight
        for _ in range(50):
            peak["threads"] = max(peak["threads"], _proc_threads())
            time.sleep(0.02)
        for t in threads:
            t.join()
        peak["threads"] = max(peak["threads"], _proc_threads())
    finally:
        node.stop()

    served = sum(1 for o, _ in results if o == "200")
    rejected = sum(1 for o, _ in results if o == "503")
    rejected_with_retry = sum(1 for o, ra in results if o == "503" and ra)
    dropped = sum(1 for o, _ in results if o not in ("200", "503"))
    if dropped:
        kinds: dict[str, int] = {}
        for o, _ in results:
            if o not in ("200", "503"):
                kinds[o] = kinds.get(o, 0) + 1
        print(f"[bench_serve] flood drops by kind: {kinds}", file=sys.stderr)
    # the boundedness assertion: proxy-side threads beyond the flood
    # clients' own. Client threads account for FLOOD_CONNS of the delta;
    # the pooled proxy may add pool + accept + a small constant, while the
    # detach build adds a thread per in-flight connection.
    proxy_extra = peak["threads"] - base_threads - FLOOD_CONNS
    flood = {
        "conns": FLOOD_CONNS,
        "pool_threads": FLOOD_THREADS,
        "served": served,
        "rejected_503": rejected,
        "rejected_with_retry_after": rejected_with_retry,
        "dropped_silently": dropped,
        "proxy_extra_threads": proxy_extra,
        "pooled": pooled,
    }
    if pooled:
        flood["flood_ok"] = (
            dropped == 0
            and served + rejected == FLOOD_CONNS
            and rejected == rejected_with_retry
            and proxy_extra <= FLOOD_THREADS + 8
        )
    else:
        flood["flood_ok"] = None  # detach baseline: report-only
    print(f"[bench_serve] flood: {flood}", file=sys.stderr)
    return flood


def _server_p99(native: dict, family: str, route: str) -> float | None:
    """p99 from the native per-route histogram (bucket upper bound —
    log-bucketed, so quantized to the ×2 schedule), or None when the
    build/route has no histogram."""
    fam = native.get("hist", {}).get(family, {})
    r = fam.get("routes", {}).get(route)
    if not r:
        return None
    from demodel_tpu.utils.metrics import hist_quantile

    return hist_quantile(fam["le"], r["counts"], 0.99)


def _hist_crosscheck(native: dict, out: dict) -> dict:
    """Server-side per-route p99 (native histograms) vs the client-observed
    p99 of the same leg: the two views of one distribution must agree
    within the log-bucket quantization (×2 per bucket) plus scheduling
    noise. Catches a silently wrong observe() unit or bucket math — a
    seconds/ms mixup is 1000× off, far outside any honest tolerance."""
    checks = {}
    for family, suffix in (("serve_request_seconds", ""),
                           ("serve_ttfb_seconds", "_ttfb")):
        sp99 = _server_p99(native, family, "peer_object")
        if sp99 is None:
            continue
        checks[f"object_server{suffix}_p99_ms"] = round(sp99 * 1e3, 3)
    sp99 = _server_p99(native, "serve_request_seconds", "peer_object")
    cp99 = out.get("object_p99_ms", 0.0) / 1e3
    if sp99 is not None and cp99 > 0:
        # server p99 is a bucket UPPER bound and excludes client-side
        # syscalls; ×8 + 2 ms absolute slack each way holds on a loaded
        # 1-CPU CI container while still catching unit/bucket bugs
        checks["hist_p99_agree"] = (
            sp99 <= cp99 * 8 + 0.002 and cp99 <= sp99 * 8 + 0.002)
    else:
        checks["hist_p99_agree"] = None  # pre-histogram build: report-only
    print(f"[bench_serve] hist cross-check: {checks}", file=sys.stderr)
    return checks


def _profile_leg(tmp: Path) -> dict:
    """The ``--profile`` leg: hot object hits with the native sampler on
    (capturing a collapsed flame during the leg) vs a ``DEMODEL_OBS=0``
    node — the overhead guard for the native-plane sampler at default Hz."""
    keys = _warm_store(tmp / "profile-node" / "cache", 1, OBJ_MB)
    path_for = lambda w, i: f"/peer/object/{keys[0]}"  # noqa: E731

    def leg(node) -> float:
        _reqs, nbytes, _l = _hammer(node.port, path_for, LEG_SECS,
                                    N_CLIENTS, expect_body=True)
        return nbytes / 1e6 / LEG_SECS

    out: dict = {"collapsed": None}
    collapsed: list[str | None] = [None]
    # the gate retries once: a 19 Hz sampler over <300 slots costs well
    # under 1%, so a miss is loopback/CI scheduling noise
    for _attempt in range(2):
        node = _node(tmp / "profile-node").start()
        try:
            grab = threading.Thread(
                target=lambda: collapsed.__setitem__(
                    0, node.profile(seconds=min(LEG_SECS, 2.0),
                                    fmt="collapsed")))
            grab.start()
            on_mbs = leg(node)
            grab.join()
        finally:
            node.stop()
        os.environ["DEMODEL_OBS"] = "0"
        try:
            node = _node(tmp / "profile-node").start()
            try:
                off_mbs = leg(node)
            finally:
                node.stop()
        finally:
            del os.environ["DEMODEL_OBS"]
        out.update({
            "off_mb_s": round(off_mbs, 2),
            "on_mb_s": round(on_mbs, 2),
            "overhead_ratio": round(on_mbs / off_mbs, 4) if off_mbs
            else None,
        })
        out["profile_ok"] = bool(off_mbs and on_mbs >= 0.95 * off_mbs)
        if out["profile_ok"]:
            break
    if collapsed[0]:
        dest = Path(os.environ.get("DEMODEL_PROFILE_OUT",
                                   "bench_serve.profile.collapsed"))
        dest.write_text(collapsed[0])
        out["collapsed"] = str(dest)
    print(f"[bench_serve] profile: {out}", file=sys.stderr)
    return out


def _raise_nofile(need: int) -> None:
    import resource

    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft < need:
        try:
            resource.setrlimit(resource.RLIMIT_NOFILE,
                               (min(need, hard), hard))
        except (ValueError, OSError) as e:
            print(f"[bench_serve] could not raise RLIMIT_NOFILE to {need}: "
                  f"{e}", file=sys.stderr)


def _ka_get(sock: socket.socket, path: str) -> tuple[int, bytes, bytes]:
    """One keep-alive GET on an already-open raw socket → (status, body,
    head). Status 0 means the peer closed before a full head arrived."""
    try:
        sock.sendall(f"GET {path} HTTP/1.1\r\nHost: x\r\n\r\n".encode())
        buf = b""
        while b"\r\n\r\n" not in buf:
            chunk = sock.recv(65536)
            if not chunk:
                return 0, b"", buf
            buf += chunk
        head, body = buf.split(b"\r\n\r\n", 1)
        status = int(head.split(b" ", 2)[1])
        cl = 0
        for line in head.split(b"\r\n"):
            if line.lower().startswith(b"content-length:"):
                cl = int(line.split(b":")[1])
        while len(body) < cl:
            chunk = sock.recv(65536)
            if not chunk:
                return 0, body, head
            body += chunk
        return status, body[:cl], head
    except OSError:
        return 0, b"", b""


def _flood_c10k(tmp: Path) -> dict:
    """Thousands of keep-alive connections on a small pool: each is served
    once (zero silent drops), parks in the reactor, costs ~no CPU while
    idle, does not dent active-request throughput, and resumes on demand;
    admission past max_conns degrades into 503+Retry-After."""
    conns_n, pool = C10K_CONNS, C10K_POOL
    _raise_nofile(2 * conns_n + 1024)
    keys = _warm_store(tmp / "c10k-node" / "cache", 2, OBJ_MB)
    max_conns = conns_n + 64
    os.environ.update({
        "DEMODEL_PROXY_THREADS": str(pool),
        "DEMODEL_PROXY_MAX_CONNS": str(max_conns),
        # the horde holds keep-alive for the whole leg; the idle bound is
        # a tuning knob, not the thing under test here
        "DEMODEL_PROXY_IDLE_TIMEOUT": "300",
    })
    try:
        node = _node(tmp / "c10k-node").start()
    finally:
        for k in ("DEMODEL_PROXY_THREADS", "DEMODEL_PROXY_MAX_CONNS",
                  "DEMODEL_PROXY_IDLE_TIMEOUT"):
            del os.environ[k]

    reactor = "sessions_parked" in node.metrics()
    out: dict = {"conns": conns_n, "pool_threads": pool, "reactor": reactor}
    socks: list[socket.socket] = []
    try:
        if not reactor:
            out["c10k_ok"] = None  # pre-reactor build: report-only
            return out

        # 1) admit the horde: every connection gets one served response
        t0 = time.perf_counter()
        drops = 0
        for i in range(conns_n):
            try:
                s = socket.create_connection(("127.0.0.1", node.port),
                                             timeout=30)
                status, body, _h = _ka_get(
                    s, f"/peer/meta/{keys[i % len(keys)]}")
                if status != 200 or not body:
                    drops += 1
                    s.close()
                else:
                    socks.append(s)
            except OSError:
                drops += 1
        out["admit_secs"] = round(time.perf_counter() - t0, 2)
        out["drops"] = drops

        # 2) the whole horde parks (gauge converges; arming is async)
        deadline = time.perf_counter() + 15
        parked = 0
        while time.perf_counter() < deadline:
            parked = node.metrics()["sessions_parked"]
            if parked >= len(socks):
                break
            time.sleep(0.05)
        out["parked_peak"] = parked

        # 3) CPU-time bound: a parked horde must cost no poll cycles — the
        # whole process (reactor + pool + this thread) stays ~idle for a
        # quiet second. The pre-reactor build burned a 5 ms poll cycle per
        # idle conn per worker slot; at 2500 conns that is CPU-visible.
        t_cpu, t_wall = time.process_time(), time.perf_counter()
        time.sleep(1.0)
        cpu_quiet = time.process_time() - t_cpu
        wall_quiet = time.perf_counter() - t_wall
        out["cpu_quiet_s"] = round(cpu_quiet, 4)
        out["cpu_quiet_wall_s"] = round(wall_quiet, 3)

        # 4) hot-hit throughput with the horde parked: active-request
        # performance must not scale with parked-connection count
        reqs, nbytes, lats = _hammer(
            node.port,
            lambda w, i: f"/peer/object/{keys[(w + i) % len(keys)]}",
            LEG_SECS, N_CLIENTS, expect_body=True)
        out["hot_mb_s_with_parked"] = round(nbytes / 1e6 / LEG_SECS, 2)
        out["hot_p99_ms_with_parked"] = round(
            _percentile(lats, 99) * 1e3, 3)

        # 5) parked conns resume on their next request (oneshot re-arm)
        resume_failures = 0
        step = max(1, len(socks) // 50)
        sampled = socks[::step][:50]
        for s in sampled:
            status, body, _h = _ka_get(s, f"/peer/meta/{keys[0]}")
            if status != 200 or not body:
                resume_failures += 1
        out["resumed"] = len(sampled)
        out["resume_failures"] = resume_failures

        # 6) admission overflow: push past max_conns — every probe gets a
        # real answer, the overflow a 503 + Retry-After
        probes = (max_conns - conns_n) + 16
        served = rejected = retry_after = other = 0
        probe_socks = []
        for _ in range(probes):
            try:
                s = socket.create_connection(("127.0.0.1", node.port),
                                             timeout=30)
                probe_socks.append(s)
                status, _body, head = _ka_get(s, f"/peer/meta/{keys[0]}")
                if status == 200:
                    served += 1
                elif status == 503:
                    rejected += 1
                    if b"Retry-After:" in head:
                        retry_after += 1
                else:
                    other += 1
            except OSError:
                other += 1
        out["overflow"] = {
            "probes": probes, "served": served, "rejected_503": rejected,
            "rejected_with_retry_after": retry_after, "other": other,
        }
        for s in probe_socks:
            s.close()

        m = node.metrics()
        out["native"] = {k: m[k] for k in
                        ("sessions_parked", "reactor_wakeups_total",
                         "sessions_rejected_total",
                         "sessions_idle_closed_total")}
        out["c10k_ok"] = (
            drops == 0
            and parked >= int(0.95 * len(socks))
            and resume_failures == 0
            and cpu_quiet < 0.35 * wall_quiet
            and other == 0
            and rejected >= 1
            and retry_after == rejected
        )
        return out
    finally:
        node.stop()
        for s in socks:
            s.close()
        print(f"[bench_serve] c10k: {out}", file=sys.stderr)


def _horde_child(argv: list[str]) -> int:
    """Slow-reader horde, run as a child process (``--horde-child port n
    key``): its fd budget and GIL are separate from the measured clients.
    Admits ``n`` keep-alive connections with an 8 KB receive buffer, sends
    one GET for the drip object each, reports ``ADMITTED <n>``, then
    trickle-drains (~1 KB per conn per ~100 ms pass ≈ 10 KB/s) until the
    driver writes ``FINISH`` on stdin, and reports ``DONE <served>
    <alive>`` — ``served`` counting conns whose response head arrived."""
    import selectors

    port, n, key = int(argv[0]), int(argv[1]), argv[2]
    _raise_nofile(n + 512)
    req = f"GET /peer/object/{key} HTTP/1.1\r\nHost: x\r\n\r\n".encode()
    socks: list[socket.socket | None] = []
    prefixes: list[bytes] = []
    admitted = 0
    for _ in range(n):
        try:
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            # pre-connect: pins the advertised window so a multi-MB
            # response can never be absorbed by kernel buffers
            s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 8192)
            s.settimeout(30)
            s.connect(("127.0.0.1", port))
            s.sendall(req)
            s.setblocking(False)
            socks.append(s)
            admitted += 1
        except OSError:
            socks.append(None)
        prefixes.append(b"")
    sys.stdout.write(f"ADMITTED {admitted}\n")
    sys.stdout.flush()

    def drain_pass(chunk: int) -> None:
        for i, s in enumerate(socks):
            if s is None:
                continue
            try:
                data = s.recv(chunk)
            except (BlockingIOError, InterruptedError):
                continue
            except OSError:
                socks[i] = None
                continue
            if not data:
                socks[i] = None
                continue
            if len(prefixes[i]) < 12:
                prefixes[i] += data[:12 - len(prefixes[i])]

    sel = selectors.DefaultSelector()
    sel.register(sys.stdin, selectors.EVENT_READ)
    finish = False
    while not finish:
        drain_pass(1024)
        if sel.select(timeout=0.1):
            finish = True  # FINISH line or driver EOF
    # bounded final sweep: any head still in flight gets a chance to land
    deadline = time.perf_counter() + 20
    while (any(s is not None and len(p) < 12
               for s, p in zip(socks, prefixes))
           and time.perf_counter() < deadline):
        drain_pass(65536)
        time.sleep(0.02)
    served = sum(1 for p in prefixes if p.startswith(b"HTTP/1.1 200"))
    alive = sum(1 for s in socks if s is not None)
    for s in socks:
        if s is not None:
            s.close()
    try:
        sys.stdout.write(f"DONE {served} {alive}\n")
        sys.stdout.flush()
    except OSError:
        pass
    return 0


def _stall_subleg(tmp: Path) -> dict:
    """Trickle clients past the write deadline: with
    ``DEMODEL_PROXY_WRITE_TIMEOUT=2`` the reactor's stall sweep must evict
    every never-reading client and count it — no worker ever blocks on
    them, no fd lingers."""
    n = 8 if SMOKE else 16
    from demodel_tpu.store import Store

    store = Store(tmp / "stall-node" / "cache" / "proxy")
    key = "stalldrip0000001"
    # 8 MB: past what sndbuf autotune (tcp_wmem caps at ~4 MB) plus the
    # pinned 8 KB rcvbuf can absorb, so the stall is real
    store.put(key, os.urandom(1 << 20) * 8,
              {"content-type": "application/octet-stream"})
    store.close()
    os.environ.update({
        "DEMODEL_PROXY_THREADS": "2",
        "DEMODEL_PROXY_WRITE_TIMEOUT": "2",
    })
    try:
        node = _node(tmp / "stall-node").start()
    finally:
        for k in ("DEMODEL_PROXY_THREADS", "DEMODEL_PROXY_WRITE_TIMEOUT"):
            del os.environ[k]
    out: dict = {"conns": n}
    socks: list[socket.socket] = []
    try:
        if "write_stall_evictions_total" not in node.metrics():
            out["evict_ok"] = None  # pre-writer build: report-only
            return out
        req = f"GET /peer/object/{key} HTTP/1.1\r\nHost: x\r\n\r\n".encode()
        for _ in range(n):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 8192)
            s.settimeout(30)
            s.connect(("127.0.0.1", node.port))
            s.sendall(req)
            socks.append(s)  # never read a byte: a pure stall
        deadline = time.perf_counter() + 30
        evictions = 0
        while time.perf_counter() < deadline:
            evictions = node.metrics()["write_stall_evictions_total"]
            if evictions >= n:
                break
            time.sleep(0.2)
        out["evictions"] = evictions
        out["evict_ok"] = evictions >= n
        return out
    finally:
        for s in socks:
            s.close()
        node.stop()
        print(f"[bench_serve] stall: {out}", file=sys.stderr)


def _c100k(tmp: Path) -> dict:
    """The C100k writer-plane leg — see the module docstring. Gates: the
    whole horde admitted with zero silent drops, every response
    writer-plane-owned (``conns_writing`` gauge), fast clients through the
    same pool unaffected (reqs flow, p99 under the SLO — with 10k writers
    on an 8-worker pool, writers holding workers would starve this leg
    outright), every tunnel spliced and echoing, the 503+Retry-After
    admission contract intact, and stalled writers evicted and counted."""
    horde_n, pool = HORDE_CONNS, HORDE_POOL
    _raise_nofile(horde_n + 8 * HORDE_TUNNELS + 4096)
    keys = _warm_store(tmp / "c100k-node" / "cache", 2, OBJ_MB)
    from demodel_tpu.store import Store

    store = Store(tmp / "c100k-node" / "cache" / "proxy")
    drip_key = "c100kdrip0000001"
    # 8 MB: past the worker-coalesce bound AND past what sndbuf autotune
    # (tcp_wmem caps at ~4 MB) plus the horde's pinned 8 KB rcvbuf can
    # absorb, so every horde response stays writer-owned all leg long
    store.put(drip_key, os.urandom(1 << 20) * 8,
              {"content-type": "application/octet-stream"})
    store.close()
    max_conns = horde_n + HORDE_TUNNELS + N_CLIENTS + 64
    os.environ.update({
        "DEMODEL_PROXY_THREADS": str(pool),
        "DEMODEL_PROXY_MAX_CONNS": str(max_conns),
        "DEMODEL_PROXY_IDLE_TIMEOUT": "300",
        # the horde legitimately trickles for the whole leg; eviction is
        # the stall sub-leg's business, not this one's
        "DEMODEL_PROXY_WRITE_TIMEOUT": "600",
    })
    try:
        node = _node(tmp / "c100k-node").start()
    finally:
        for k in ("DEMODEL_PROXY_THREADS", "DEMODEL_PROXY_MAX_CONNS",
                  "DEMODEL_PROXY_IDLE_TIMEOUT",
                  "DEMODEL_PROXY_WRITE_TIMEOUT"):
            del os.environ[k]
    writer = "conns_writing" in node.metrics()
    out: dict = {"horde_conns": horde_n, "pool_threads": pool,
                 "tunnels": HORDE_TUNNELS, "writer": writer}
    child = None
    lsock = None
    tun_socks: list[socket.socket] = []
    held_upstream: list[socket.socket] = []
    try:
        if not writer:
            out["c100k_ok"] = None  # pre-writer build: report-only
            return out

        # 1) CONNECT tunnels: reactor-spliced, two fds and zero workers
        # each; one byte echoed both ways proves each pump end-to-end
        lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        lsock.bind(("127.0.0.1", 0))
        lsock.listen(HORDE_TUNNELS)
        up_port = lsock.getsockname()[1]

        def upstream() -> None:
            for _ in range(HORDE_TUNNELS):
                try:
                    c, _ = lsock.accept()
                except OSError:
                    return
                c.settimeout(20)
                try:
                    d = c.recv(16)
                    if d:
                        c.sendall(d)
                except OSError:
                    pass
                held_upstream.append(c)  # hold the tunnel open

        upt = threading.Thread(target=upstream)
        upt.start()
        tun_echoed = 0
        for _ in range(HORDE_TUNNELS):
            s = socket.create_connection(("127.0.0.1", node.port),
                                         timeout=20)
            s.settimeout(20)
            tun_socks.append(s)
            s.sendall(f"CONNECT 127.0.0.1:{up_port} HTTP/1.1\r\n\r\n"
                      .encode())
            buf = b""
            while b"\r\n\r\n" not in buf:
                chunk = s.recv(4096)
                if not chunk:
                    break
                buf += chunk
            if b"200 Connection Established" not in buf:
                continue
            try:
                s.sendall(b"ping")
                if s.recv(16) == b"ping":
                    tun_echoed += 1
            except OSError:
                pass
        upt.join(timeout=30)
        out["tunnels_echoed"] = tun_echoed

        # 2) admit the horde from the child process
        child = subprocess.Popen(
            [sys.executable, str(Path(__file__).resolve()), "--horde-child",
             str(node.port), str(horde_n), drip_key],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True)
        t0 = time.perf_counter()
        parts = (child.stdout.readline() or "").split()
        admitted = int(parts[1]) if parts and parts[0] == "ADMITTED" else 0
        out["admitted"] = admitted
        out["admit_secs"] = round(time.perf_counter() - t0, 2)

        # 3) every admitted response lands in the writer plane (arming is
        # async behind the worker pool — the gauge converges)
        deadline = time.perf_counter() + 60
        writing = 0
        m = node.metrics()
        while time.perf_counter() < deadline:
            m = node.metrics()
            writing = m["conns_writing"]
            if writing >= admitted:
                break
            time.sleep(0.1)
        out["conns_writing_peak"] = writing
        out["tunnels_spliced"] = m["tunnels_spliced"]

        # 4) fast clients through the same pool while the horde trickles
        reqs, nbytes, lats = _hammer(
            node.port,
            lambda w, i: f"/peer/object/{keys[(w + i) % len(keys)]}",
            LEG_SECS, N_CLIENTS, expect_body=True)
        out["fast_mb_s_with_horde"] = round(nbytes / 1e6 / LEG_SECS, 2)
        out["fast_p99_ms_with_horde"] = round(
            _percentile(lats, 99) * 1e3, 3)
        out["fast_reqs_with_horde"] = reqs

        # 5) admission past max_conns: a real answer for every probe, the
        # overflow a 503 + Retry-After — never a silent drop
        probes = max(16, max_conns - admitted - HORDE_TUNNELS + 16)
        served = rejected = retry_after = other = 0
        probe_socks = []
        for _ in range(probes):
            try:
                s = socket.create_connection(("127.0.0.1", node.port),
                                             timeout=30)
                probe_socks.append(s)
                status, _body, head = _ka_get(s, f"/peer/meta/{keys[0]}")
                if status == 200:
                    served += 1
                elif status == 503:
                    rejected += 1
                    if b"Retry-After:" in head:
                        retry_after += 1
                else:
                    other += 1
            except OSError:
                other += 1
        out["overflow"] = {
            "probes": probes, "served": served, "rejected_503": rejected,
            "rejected_with_retry_after": retry_after, "other": other,
        }
        for s in probe_socks:
            s.close()

        # 6) finish: the child reports response heads seen + conns alive
        child.stdin.write("FINISH\n")
        child.stdin.flush()
        parts = (child.stdout.readline() or "").split()
        done = len(parts) == 3 and parts[0] == "DONE"
        out["horde_served_heads"] = int(parts[1]) if done else 0
        out["horde_alive_at_finish"] = int(parts[2]) if done else 0
        out["horde_drops"] = horde_n - out["horde_served_heads"]
        child.wait(timeout=60)
        child = None

        m = node.metrics()
        out["native"] = {k: m[k] for k in (
            "conns_writing", "tunnels_spliced", "sendfile_bytes_total",
            "splice_bytes_total", "write_stall_evictions_total",
            "ktls_sends_total") if k in m}
    finally:
        if child is not None and child.poll() is None:
            child.kill()
        for s in tun_socks + held_upstream:
            s.close()
        if lsock is not None:
            lsock.close()
        node.stop()
        print(f"[bench_serve] c100k: {out}", file=sys.stderr)

    stall = _stall_subleg(tmp)
    out["stall"] = stall
    out["c100k_ok"] = (
        admitted == horde_n
        and out["horde_drops"] == 0
        and out["conns_writing_peak"] >= int(0.98 * admitted)
        and tun_echoed == HORDE_TUNNELS
        and out["tunnels_spliced"] == HORDE_TUNNELS
        and reqs > 0
        and out["fast_p99_ms_with_horde"] <= FAST_P99_SLO_MS
        and other == 0
        and rejected >= 1
        and retry_after == rejected
        and stall["evict_ok"] is True
    )
    return out


def main() -> int:
    t_setup = time.perf_counter()
    with tempfile.TemporaryDirectory() as td:
        tmp = Path(td)
        keys = _warm_store(tmp / "node" / "cache", N_OBJECTS, OBJ_MB)
        # the measured leg gets an explicit pool ≥ clients so keep-alive
        # clients never queue behind each other — the comparison against
        # the detach build is then socket-for-socket fair
        os.environ["DEMODEL_PROXY_THREADS"] = str(max(N_CLIENTS, 2))
        try:
            node = _node(tmp / "node").start()
        finally:
            del os.environ["DEMODEL_PROXY_THREADS"]
        try:
            port = node.port
            print(f"[bench_serve] node up on :{port} after "
                  f"{time.perf_counter() - t_setup:.2f}s "
                  f"({N_OBJECTS}×{OBJ_MB} MB warmed)", file=sys.stderr)
            # one warmup pass per endpoint (open fds, fault the page cache)
            _hammer(port, lambda w, i: f"/peer/object/{keys[0]}", 0.2, 2, True)

            out: dict = {}
            out.update(_leg(
                "object", port,
                lambda w, i: f"/peer/object/{keys[(w + i) % len(keys)]}",
                LEG_SECS, N_CLIENTS, expect_body=True))
            out.update(_leg(
                "meta", port,
                lambda w, i: f"/peer/meta/{keys[(w + i) % len(keys)]}",
                LEG_SECS / 2, N_CLIENTS, expect_body=True))
            out.update(_leg(
                "index", port, lambda w, i: "/peer/index",
                LEG_SECS / 2, N_CLIENTS, expect_body=True))
            native = node.metrics()
            out.update(_hist_crosscheck(native, out))
        finally:
            node.stop()

        flood = _flood(tmp)
        c10k = _flood_c10k(tmp)
        c100k = _c100k(tmp)
        profile = _profile_leg(tmp) if PROFILE else None
        if c10k.get("hot_mb_s_with_parked") and out.get("object_mb_s"):
            # active-request throughput with ~C10K conns parked vs the
            # plain leg — the "parked conns are free" claim, quantified
            c10k["hot_vs_unparked_ratio"] = round(
                c10k["hot_mb_s_with_parked"] / out["object_mb_s"], 3)
        if c100k.get("fast_mb_s_with_horde") and out.get("object_mb_s"):
            # fast-client throughput with the slow-reader horde trickling
            # vs the plain leg — the "writers hold zero workers" claim
            c100k["fast_vs_unparked_ratio"] = round(
                c100k["fast_mb_s_with_horde"] / out["object_mb_s"], 3)

    result = {
        "metric": "serve_hot_hit_throughput",
        "value": out["object_mb_s"],
        "unit": "MB/s",
        "vs_baseline": 0.0,  # first serve-plane datapoint — no prior anchor
        "clients": N_CLIENTS,
        "objects": N_OBJECTS,
        "object_mb": OBJ_MB,
        "pooled": flood.get("pooled", False),
        "reactor": c10k.get("reactor", False),
        **out,
        "flood": flood,
        "c10k": c10k,
        "c100k": c100k,
        **({"profile": profile} if profile is not None else {}),
        **({"native_serve_bytes_total": native["serve_bytes_total"]}
           if "serve_bytes_total" in native else {}),
    }
    print(json.dumps(result))
    if flood["flood_ok"] is False:
        print("[bench_serve] FLOOD CONTRACT VIOLATED", file=sys.stderr)
        return 1
    if c10k.get("c10k_ok") is False:
        print("[bench_serve] C10K CONTRACT VIOLATED", file=sys.stderr)
        return 1
    if c100k.get("c100k_ok") is False:
        print("[bench_serve] C100K WRITER CONTRACT VIOLATED",
              file=sys.stderr)
        return 1
    if out.get("hist_p99_agree") is False:
        print("[bench_serve] HISTOGRAM/CLIENT P99 DISAGREE", file=sys.stderr)
        return 1
    if profile is not None and profile.get("profile_ok") is False:
        print("[bench_serve] PROFILER OVERHEAD GATE VIOLATED",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    if "--horde-child" in sys.argv:
        at = sys.argv.index("--horde-child")
        sys.exit(_horde_child(sys.argv[at + 1:at + 4]))
    sys.exit(main())
