"""Tiered-store benchmark driver — prints ONE JSON line (same contract
as ``bench_serve.py``; that driver times the serve plane, this one times
the TIER plane: single-flight admission on the cold miss edge and the
mmap hot tier on the re-read edge).

Scenario legs:

  herd   the thundering herd: ``DEMODEL_STORE_CLIENTS`` concurrent cold
         clients all ``TieredStore.read`` one key against a COUNTING
         origin shim that streams the body slowly (a realistic landing
         stream). The contract: exactly ONE origin fetch, every client
         byte-exact, and the cohort finishes with the landing stream —
         waiters ride the leader's progress watermark instead of
         serializing behind the commit (a serialized implementation
         takes ~N× the leader's time and fails the ratio gate).
  hot    re-reads served from the mmap hot tier (RAM), MB/s;
  disk   the same re-reads with promotion disabled (1-byte hot budget),
         MB/s — the hot-vs-disk spread the tier exists to buy.

Env knobs: DEMODEL_STORE_OBJ_MB (default 16), DEMODEL_STORE_CLIENTS
(128 — the acceptance floor is ≥100 cold clients), DEMODEL_STORE_SECS
(2.0 per re-read leg), DEMODEL_STORE_CHUNK_KB (256 origin chunk),
DEMODEL_STORE_STALL_MS (8 per-chunk origin throttle). ``--smoke`` (or
DEMODEL_STORE_SMOKE=1) shrinks everything for CI; the rc gates (one
origin fetch, bytes-exact, herd ratio) hold at every size.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import tempfile
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def _env_f(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


SMOKE = ("--smoke" in sys.argv
         or os.environ.get("DEMODEL_STORE_SMOKE", "").strip() == "1")
PROFILE = ("--profile" in sys.argv
           or os.environ.get("DEMODEL_STORE_PROFILE", "").strip() == "1")
OBJ_MB = int(_env_f("DEMODEL_STORE_OBJ_MB", 4 if SMOKE else 16))
N_CLIENTS = int(_env_f("DEMODEL_STORE_CLIENTS", 32 if SMOKE else 128))
LEG_SECS = _env_f("DEMODEL_STORE_SECS", 0.5 if SMOKE else 2.0)
CHUNK_KB = int(_env_f("DEMODEL_STORE_CHUNK_KB", 256))
STALL_MS = _env_f("DEMODEL_STORE_STALL_MS", 2 if SMOKE else 8)


def _percentile(sorted_vals: list[float], pct: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              int(round(pct / 100 * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


class CountingOrigin:
    """The origin shim: a deterministic body streamed in throttled
    chunks, counting every fetch and every byte it actually shipped
    (a resumed fetch at offset>0 ships only the tail — the counter
    proves waiters cost zero origin bytes)."""

    def __init__(self, body: bytes):
        self.body = body
        self.fetches = 0
        self.bytes_shipped = 0
        self._lock = threading.Lock()

    def fetch(self, key: str, offset: int):
        with self._lock:
            self.fetches += 1
        chunk = CHUNK_KB << 10
        for i in range(offset, len(self.body), chunk):
            piece = self.body[i:i + chunk]
            with self._lock:
                self.bytes_shipped += len(piece)
            yield piece
            if STALL_MS:
                time.sleep(STALL_MS / 1e3)


def _herd(tmp: Path) -> dict:
    from demodel_tpu import tier
    from demodel_tpu.store import Store
    from demodel_tpu.utils import metrics

    body = os.urandom(1 << 20) * OBJ_MB
    digest = hashlib.sha256(body).hexdigest()
    origin = CountingOrigin(body)
    store = Store(tmp / "herd")
    ts = tier.TieredStore(store, name="bench-herd")
    before = metrics.HUB.snapshot()

    gate = threading.Barrier(N_CLIENTS)
    lock = threading.Lock()
    done_at: list[float] = []
    bad: list[str] = []

    def client() -> None:
        try:
            gate.wait(timeout=60)
            got = ts.read("herdobj000000001", fetch=origin.fetch,
                          expected_digest=digest)
            ok = got == body
        except BaseException as e:  # noqa: BLE001 — counted as a failure
            ok = False
            with lock:
                bad.append(f"{type(e).__name__}: {e}")
        t = time.perf_counter()
        with lock:
            done_at.append(t)
            if not ok and not bad:
                bad.append("byte mismatch")

    t0 = time.perf_counter()
    threads = [threading.Thread(target=client) for _ in range(N_CLIENTS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    after = metrics.HUB.snapshot()
    ts.close()
    store.close()

    lat = sorted(t - t0 for t in done_at)
    first, last = lat[0], lat[-1]
    counters = {}
    for name in ("singleflight_leaders_total", "singleflight_waiters_total",
                 "singleflight_handoffs_total"):
        counters[name] = after.get(name, 0) - before.get(name, 0)
    herd = {
        "clients": N_CLIENTS,
        "object_mb": OBJ_MB,
        "origin_fetches": origin.fetches,
        "origin_mb_shipped": round(origin.bytes_shipped / 1e6, 2),
        "bad_clients": len(bad),
        "first_done_s": round(first, 3),
        "last_done_s": round(last, 3),
        "done_p50_s": round(_percentile(lat, 50), 3),
        # waiters ride the landing stream: the cohort finishes WITH the
        # stream, not serialized after it. The bound is generous (GIL
        # contention spreads N waiters each copying the object out of the
        # partial) but still orders of magnitude under the ~N× a
        # refetch-per-client implementation would take — and THAT failure
        # also trips the origin_fetches gate above.
        "cohort_spread_ratio": round(last / first, 3) if first else None,
        "singleflight": counters,
    }
    herd["herd_ok"] = (
        origin.fetches == 1
        and not bad
        and origin.bytes_shipped == len(body)
        and counters["singleflight_leaders_total"] >= 1
        and counters["singleflight_waiters_total"] == N_CLIENTS - 1
        and (first == 0 or last <= first * 3.5 + 1.0)
    )
    if bad:
        print(f"[bench_store] herd failures: {bad[:3]}", file=sys.stderr)
    print(f"[bench_store] herd: {herd}", file=sys.stderr)
    return herd


def _reread(tmp: Path) -> dict:
    """Hot-tier vs disk re-read throughput over one warmed object."""
    from demodel_tpu import tier
    from demodel_tpu.store import Store

    body = os.urandom(1 << 20) * OBJ_MB
    store = Store(tmp / "reread")
    store.put("rereadobj00000001", body,
              {"content-type": "application/octet-stream"})

    def leg(ts: tier.TieredStore) -> tuple[float, float]:
        # one warmup read (faults the page cache / maps the object)
        assert ts.read("rereadobj00000001") == body
        stop = time.perf_counter() + LEG_SECS
        reads = 0
        t0 = time.perf_counter()
        while time.perf_counter() < stop:
            if len(ts.read("rereadobj00000001")) != len(body):
                raise AssertionError("short re-read")
            reads += 1
        secs = time.perf_counter() - t0
        return reads / secs, reads * len(body) / 1e6 / secs

    hot_ts = tier.TieredStore(store, name="bench-hot")
    hot_reqs, hot_mbs = leg(hot_ts)
    hot_served_ram = hot_ts.hot.contains("rereadobj00000001")
    hot_ts.close()
    # a 1-byte budget refuses every promotion — the same reads now take
    # the disk path (store.get) every time
    disk_ts = tier.TieredStore(
        store, hot_budget=tier.TierBudget("bench-disk", 1),
        name="bench-disk")
    disk_reqs, disk_mbs = leg(disk_ts)
    disk_ts.close()
    store.close()

    out = {
        "hot_reads_s": round(hot_reqs, 1),
        "hot_mb_s": round(hot_mbs, 2),
        "hot_served_from_ram": hot_served_ram,
        "disk_reads_s": round(disk_reqs, 1),
        "disk_mb_s": round(disk_mbs, 2),
        "hot_vs_disk_ratio": round(hot_mbs / disk_mbs, 3) if disk_mbs else None,
        "reread_ok": hot_served_ram and hot_mbs > 0 and disk_mbs > 0,
    }
    print(f"[bench_store] reread: {out}", file=sys.stderr)
    return out


def _profile_leg(tmp: Path) -> dict:
    """The ``--profile`` leg: hot re-reads with the continuous profiler
    off, then on (capturing a collapsed flame next to the BENCH json) —
    the overhead guard for the Python-plane sampler at default Hz."""
    from demodel_tpu import tier
    from demodel_tpu.store import Store
    from demodel_tpu.utils import profiler

    body = os.urandom(1 << 20) * OBJ_MB
    store = Store(tmp / "profleg")
    store.put("proflegobj0000001", body,
              {"content-type": "application/octet-stream"})

    def leg() -> float:
        assert ts.read("proflegobj0000001") == body
        stop = time.perf_counter() + LEG_SECS
        reads = 0
        t0 = time.perf_counter()
        while time.perf_counter() < stop:
            if len(ts.read("proflegobj0000001")) != len(body):
                raise AssertionError("short re-read")
            reads += 1
        return reads * len(body) / 1e6 / (time.perf_counter() - t0)

    ts = tier.TieredStore(store, name="bench-profile")
    out: dict = {"hz": None, "collapsed": None}
    try:
        # the gate retries once: a 19 Hz sampler costs well under 1%, so
        # a miss is loopback/CI scheduling noise, not profiler overhead
        for _attempt in range(2):
            profiler.stop()
            off_mbs = leg()
            prof = profiler.ensure()
            if prof is None:  # DEMODEL_OBS=0: nothing to measure
                out.update({"profile_ok": None, "off_mb_s": round(off_mbs, 2)})
                return out
            out["hz"] = prof.hz
            on_mbs = leg()
            cap = profiler.capture(seconds=0)  # cumulative = this leg
            profiler.stop()
            out.update({
                "off_mb_s": round(off_mbs, 2),
                "on_mb_s": round(on_mbs, 2),
                "overhead_ratio": round(on_mbs / off_mbs, 4) if off_mbs
                else None,
                "samples": cap["samples"] if cap else 0,
            })
            out["profile_ok"] = bool(off_mbs and on_mbs >= 0.95 * off_mbs)
            if out["profile_ok"]:
                break
        if cap:
            dest = Path(os.environ.get("DEMODEL_PROFILE_OUT",
                                       "bench_store.profile.collapsed"))
            dest.write_text(profiler.collapse(cap))
            out["collapsed"] = str(dest)
    finally:
        ts.close()
        store.close()
    print(f"[bench_store] profile: {out}", file=sys.stderr)
    return out


def main() -> int:
    with tempfile.TemporaryDirectory() as td:
        tmp = Path(td)
        herd = _herd(tmp)
        reread = _reread(tmp)
        profile = _profile_leg(tmp) if PROFILE else None

    result = {
        "metric": "store_herd_origin_fetches",
        "value": herd["origin_fetches"],
        "unit": "fetches",
        "vs_baseline": 0.0,  # first tier-plane datapoint — no prior anchor
        "smoke": SMOKE,
        "herd": herd,
        "reread": reread,
    }
    if profile is not None:
        result["profile"] = profile
    print(json.dumps(result))
    if not herd["herd_ok"]:
        print("[bench_store] HERD CONTRACT VIOLATED", file=sys.stderr)
        return 1
    if not reread["reread_ok"]:
        print("[bench_store] REREAD CONTRACT VIOLATED", file=sys.stderr)
        return 1
    if profile is not None and profile.get("profile_ok") is False:
        print("[bench_store] PROFILER OVERHEAD GATE VIOLATED",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
