#!/usr/bin/env python
"""Swarm-pull benchmark driver — prints ONE JSON line (same contract as
``bench.py`` / ``bench_serve.py``).

Scenario: the pod-scale cold pull. A warm origin node sits behind a
rate-limited shim (``ChaosPeer(throttle_bps=...)`` — the constrained
origin link that makes the swarm claim measurable on localhost), and two
legs pull the same manifest-shaped file set through it:

  single   one host, no swarm: every byte crosses the origin link once
           per host — the pre-swarm baseline shape;
  swarm    N simulated hosts (each a ``SwarmScheduler`` + a restore
           server exposing its chunk board): disjoint ring-owned chunk
           sets off origin, everything else cross-filled peer-to-peer.

Reported: wall-clock per leg + speedup, aggregate origin BODY bytes per
leg and the swarm leg's origin-bytes/manifest ratio (the paper claim:
≈ 1×, not N×), peer-fill share, re-owned chunk count, and bytes-exact
digests on every host. ``swarm_ok`` asserts the acceptance bounds —
origin ratio ≤ 1.25 and wall-clock ≤ 0.5× single-host (smoke: ≤ 0.8×,
the tiny sizes leave more fixed overhead in the ratio).

Env knobs: DEMODEL_SWARM_BENCH_HOSTS (4), DEMODEL_SWARM_BENCH_FILES (3),
DEMODEL_SWARM_BENCH_FILE_MB (16; smoke 4), DEMODEL_SWARM_BENCH_THROTTLE_MBPS
(40; smoke 25). ``--smoke`` (or DEMODEL_SWARM_SMOKE=1) shrinks everything
for CI.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import tempfile
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

SMOKE = ("--smoke" in sys.argv
         or os.environ.get("DEMODEL_SWARM_SMOKE", "").strip() == "1")


def _env_i(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    try:
        return int(raw) if raw else default
    except ValueError:
        return default


N_HOSTS = _env_i("DEMODEL_SWARM_BENCH_HOSTS", 4)
N_FILES = _env_i("DEMODEL_SWARM_BENCH_FILES", 3)
FILE_MB = _env_i("DEMODEL_SWARM_BENCH_FILE_MB", 4 if SMOKE else 16)
# The origin link must be the BOTTLENECK for the simulation to model the
# pod cold-pull (a WAN origin vs fast DCN cross-fill): slow enough that
# one manifest's link time dominates the swarm's localhost CPU work even
# on a small CI box. 6 MB/s full / 12 MB/s smoke keeps the full single
# leg ~8 s and the claim measurable.
THROTTLE = _env_i("DEMODEL_SWARM_BENCH_THROTTLE_MBPS", 12 if SMOKE else 6)
CHUNK_MB = _env_i("DEMODEL_SWARM_CHUNK_MB", 1 if SMOKE else 2)


def _origin_node(tmp: Path):
    from demodel_tpu.config import ProxyConfig
    from demodel_tpu.proxy import ProxyServer
    from demodel_tpu.store import Store

    cfg = ProxyConfig(
        host="127.0.0.1", port=0, mitm_hosts=[], no_mitm=True,
        cache_dir=tmp / "origin-cache", data_dir=tmp / "origin-data")
    store = Store(cfg.cache_dir / "proxy")
    files = []
    try:
        for i in range(N_FILES):
            body = os.urandom(1 << 20) * FILE_MB
            key = f"swarmbench{i:04d}"
            store.put(key, body,
                      {"content-type": "application/octet-stream"})
            files.append({"key": key, "size": len(body),
                          "sha256": hashlib.sha256(body).hexdigest()})
    finally:
        store.close()
    node = ProxyServer(cfg, verbose=False)
    node.start()
    return node, files


def _digest_all(sched, files) -> dict[str, str]:
    """Hash what landed on one host's board (the bytes-exact proof) —
    called OUTSIDE the timed window: verification sha256 time is not
    transfer time."""
    out = {}
    for f in files:
        buf = bytearray(f["size"])
        sched.read_into(f["key"], memoryview(buf), 0)
        out[f["key"]] = hashlib.sha256(buf).hexdigest()
    return out


def _single_leg(origin_url: str, files) -> tuple[float, bool]:
    """One host, one scheduler that owns everything: the no-swarm
    baseline through the same code path and the same throttled link."""
    from demodel_tpu.sink.remote import PeerBlobReader, SwarmScheduler

    sched = SwarmScheduler("bench-single", "solo",
                          {"solo": "http://127.0.0.1:1"})
    try:
        for f in files:
            sched.add_file(f["key"], f["size"],
                           PeerBlobReader(origin_url, f["key"], f["size"],
                                          streams=1))
        sched.start()
        t0 = time.monotonic()
        sched.fetch_all()
        secs = time.monotonic() - t0
        digests = _digest_all(sched, files)
    finally:
        sched.close()
    ok = all(digests[f["key"]] == f["sha256"] for f in files)
    return secs, ok


def _swarm_leg(origin_url: str, files) -> tuple[float, bool, int]:
    from demodel_tpu.restore.server import RestoreRegistry, RestoreServer
    from demodel_tpu.sink.remote import PeerBlobReader, SwarmScheduler
    from demodel_tpu.store import Store

    tmp = Path(tempfile.mkdtemp(prefix="swarmbench-hosts-"))
    servers, stores, scheds = [], [], []
    try:
        participants = {}
        for i in range(N_HOSTS):
            hid = f"host{i}"
            st = Store(tmp / hid)
            srv = RestoreServer(RestoreRegistry(st),
                                host="127.0.0.1").start()
            stores.append(st)
            servers.append(srv)
            participants[hid] = f"http://127.0.0.1:{srv.port}"
        for hid in participants:
            s = SwarmScheduler("bench-swarm", hid, participants)
            for f in files:
                # streams=1: each host gets ONE origin connection, the
                # "one DCN link per host" shape the simulation models
                s.add_file(f["key"], f["size"],
                           PeerBlobReader(origin_url, f["key"], f["size"],
                                          streams=1))
            scheds.append(s)
        for s in scheds:
            s.start()
        errors: list = []

        def run(s):
            try:
                s.fetch_all()
            except Exception as e:  # noqa: BLE001 — reported in the JSON
                errors.append(f"{s.self_id}: {e}")

        threads = [threading.Thread(target=run, args=(s,)) for s in scheds]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        secs = time.monotonic() - t0
        # verification outside the clock: every host, every file, exact
        results = {s.self_id: _digest_all(s, files) for s in scheds}
        refetched = sum(s.stats()["chunks_refetched"] for s in scheds)
        ok = (not errors and len(results) == N_HOSTS
              and all(d[f["key"]] == f["sha256"]
                      for d in results.values() for f in files))
        return secs, ok, refetched
    finally:
        for s in scheds:
            s.close()
        for srv in servers:
            srv.stop()
        for st in stores:
            st.close()


def main() -> int:
    os.environ.setdefault("DEMODEL_SWARM_CHUNK_MB", str(CHUNK_MB))
    os.environ.setdefault("DEMODEL_SWARM_GOSSIP_MS", "150")

    sys.path.insert(0, str(REPO / "tests"))
    from chaoshttp import ChaosPeer, FaultPlan

    from demodel_tpu.utils import metrics as m
    from demodel_tpu.utils.faults import PeerHealth

    tmp = Path(tempfile.mkdtemp(prefix="swarmbench-"))
    node, files = _origin_node(tmp)
    total = sum(f["size"] for f in files)
    throttle_bps = THROTTLE << 20
    out: dict = {
        "metric": "swarm_bench", "smoke": SMOKE, "hosts": N_HOSTS,
        "files": N_FILES, "total_mb": round(total / (1 << 20), 1),
        "chunk_mb": CHUNK_MB, "throttle_mbps": THROTTLE,
    }
    try:
        # leg 1: single host, no swarm
        m.HUB.reset()
        PeerHealth.reset_shared()
        with ChaosPeer(node.url, FaultPlan(),
                       throttle_bps=throttle_bps) as origin:
            single_secs, single_ok = _single_leg(origin.url, files)
            out["single_secs"] = round(single_secs, 3)
            out["single_ok"] = single_ok
            out["origin_bytes_single"] = origin.bytes_served

        # leg 2: the swarm
        m.HUB.reset()
        PeerHealth.reset_shared()
        with ChaosPeer(node.url, FaultPlan(),
                       throttle_bps=throttle_bps) as origin:
            swarm_secs, swarm_exact, refetched = _swarm_leg(origin.url,
                                                            files)
            out["swarm_secs"] = round(swarm_secs, 3)
            out["swarm_bytes_exact"] = swarm_exact
            out["origin_bytes_swarm"] = origin.bytes_served
    finally:
        node.stop()

    origin_chunk = m.HUB.get("swarm_origin_bytes_total")
    peer_fill = m.HUB.get("swarm_peer_bytes_total")
    out["origin_chunk_bytes"] = int(origin_chunk)
    out["peer_fill_bytes"] = int(peer_fill)
    out["chunks_refetched"] = refetched
    out["origin_ratio_swarm"] = round(out["origin_bytes_swarm"] / total, 3)
    out["peer_fill_share"] = round(
        peer_fill / max(1.0, peer_fill + origin_chunk * 1.0), 3)
    out["speedup"] = round(single_secs / max(swarm_secs, 1e-9), 2)
    wall_bound = 0.8 if SMOKE else 0.5
    out["swarm_ok"] = bool(
        single_ok and swarm_exact
        and out["origin_ratio_swarm"] <= 1.25
        and swarm_secs <= wall_bound * single_secs)
    print(json.dumps(out))
    return 0 if out["swarm_ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
