#!/usr/bin/env python
"""Adaptive-pull-tuning benchmark driver — prints ONE JSON line (same
contract as ``bench.py`` / ``bench_serve.py`` / ``bench_swarm.py``).

Scenario: the closed loop's proof. A warm origin node sits behind a
per-connection rate-limited, fault-injected shim (``ChaosPeer``:
throttle + a couple of mid-pull stalls — the constrained flaky link the
tuner exists for), and two leg families pull the same file set through
it with the SAME windowed-fetch driver:

  fixed     a sweep of hand-picked (streams, window) configs, tuner off
            — the envelope the adaptive leg is judged against;
  adaptive  knobs start at the env defaults and a live
            :class:`~demodel_tpu.sink.tuner.PullTuner` moves them from
            the telemetry plane's sliding-window signals, over several
            passes so the convergence (not just the cold ramp) shows.

EVERY pass — fixed or adaptive — runs against a FRESH shim with the
identical fault plan and throttle, so both leg families face the same
faults per pass and the comparison is about the knobs, nothing else
(the tuner itself survives across the adaptive passes: convergence is
the point). Because the shim throttles PER CONNECTION, per-peer stream
concurrency is real aggregate bandwidth — the knob the controller must
discover (the native fan-out clamps to one stream per 4 MB of window,
so the file size bounds the reachable concurrency).

Reported: per-config fixed throughputs, per-pass adaptive throughputs,
the converged adaptive rate (median of the last 3 passes), tuner
decision count + final knobs + span-event visibility, and ``tuner_ok``:
converged ≥ 0.9× the best fixed point and overall ≥ 1.2× the worst
(smoke: 0.7× / 0.9× — smoke sizes leave little stream headroom, so it
gates sanity + observability, not the convergence claim).

Env knobs: DEMODEL_TUNE_BENCH_FILES (2), DEMODEL_TUNE_BENCH_FILE_MB
(16; smoke 8), DEMODEL_TUNE_BENCH_THROTTLE_MBPS per connection (6;
smoke 10), DEMODEL_TUNE_BENCH_PASSES (6; smoke 4). ``--smoke`` (or
DEMODEL_TUNE_SMOKE=1) shrinks everything for CI.
"""

from __future__ import annotations

import hashlib
import json
import os
import statistics
import sys
import tempfile
import time
from pathlib import Path
from types import SimpleNamespace

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

SMOKE = ("--smoke" in sys.argv
         or os.environ.get("DEMODEL_TUNE_SMOKE", "").strip() == "1")


def _env_i(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    try:
        return int(raw) if raw else default
    except ValueError:
        return default


N_FILES = _env_i("DEMODEL_TUNE_BENCH_FILES", 2)
FILE_MB = _env_i("DEMODEL_TUNE_BENCH_FILE_MB", 8 if SMOKE else 16)
THROTTLE = _env_i("DEMODEL_TUNE_BENCH_THROTTLE_MBPS", 10 if SMOKE else 6)
PASSES = _env_i("DEMODEL_TUNE_BENCH_PASSES", 4 if SMOKE else 6)

#: the hand-picked sweep the adaptive leg is judged against: a floor
#: (single stream, small windows), the untouched env defaults, and an
#: aggressive point (max streams, big windows)
FIXED_CONFIGS = (
    ("floor", 1, 4 << 20),
    ("default", None, None),   # resolved from env at run time
    ("aggressive", 8, 64 << 20),
)


def _origin_node(tmp: Path):
    from demodel_tpu.config import ProxyConfig
    from demodel_tpu.proxy import ProxyServer
    from demodel_tpu.store import Store

    cfg = ProxyConfig(
        host="127.0.0.1", port=0, mitm_hosts=[], no_mitm=True,
        cache_dir=tmp / "origin-cache", data_dir=tmp / "origin-data")
    store = Store(cfg.cache_dir / "proxy")
    files = []
    try:
        for i in range(N_FILES):
            body = os.urandom(1 << 20) * FILE_MB
            key = f"tunebench{i:04d}"
            store.put(key, body,
                      {"content-type": "application/octet-stream"})
            files.append({"key": key, "size": len(body),
                          "sha256": hashlib.sha256(body).hexdigest()})
    finally:
        store.close()
    node = ProxyServer(cfg, verbose=False)
    node.start()
    return node, files


def _plan():
    from chaoshttp import FaultPlan, FaultSpec

    # a couple of mid-body resets per leg: enough that the wire is
    # genuinely faulty (window resume + retry accounting runs), mild
    # enough that the throughput comparison stays about the knobs
    return FaultPlan(FaultSpec(kind="stall", path="/peer/object",
                               times=2, stall_secs=0.3))


def _fetch_pass(url: str, files, knobs) -> tuple[float, float, bool]:
    """One pass over the whole file set with the windowed-fetch driver
    (the same loop the pipelined pull's fetch stage uses). Returns
    (secs, MB/s, bytes_exact) — digests computed OUTSIDE the clock."""
    from demodel_tpu.sink.remote import PeerBlobReader
    from demodel_tpu.sink.tuner import fetch_windows

    bufs = []
    t0 = time.monotonic()
    for f in files:
        reader = PeerBlobReader(url, f["key"], f["size"], streams=1)
        buf = bytearray(f["size"])
        fetch_windows(reader, f["key"], buf, 0, knobs)
        bufs.append(buf)
    secs = time.monotonic() - t0
    total = sum(f["size"] for f in files)
    ok = all(hashlib.sha256(b).hexdigest() == f["sha256"]
             for b, f in zip(bufs, files))
    return secs, total / secs / (1 << 20), ok


def _reset_state():
    from demodel_tpu.utils import metrics as m
    from demodel_tpu.utils.faults import PeerHealth

    m.HUB.reset()
    PeerHealth.reset_shared()


def main() -> int:  # noqa: C901
    os.environ.setdefault("DEMODEL_RETRY_BASE_MS", "20")
    os.environ.setdefault("DEMODEL_TUNER_TICK_MS", "200")
    os.environ.setdefault("DEMODEL_TUNER_WINDOW_S", "3")
    os.environ.setdefault("DEMODEL_TELEMETRY_MIN_GAP_MS", "100")
    sys.path.insert(0, str(REPO / "tests"))
    from chaoshttp import ChaosPeer

    from demodel_tpu.parallel import peer as peer_mod
    from demodel_tpu.sink.tuner import PullTuner
    from demodel_tpu.utils import metrics as m
    from demodel_tpu.utils import trace

    tmp = Path(tempfile.mkdtemp(prefix="tunebench-"))
    node, files = _origin_node(tmp)
    total_mb = sum(f["size"] for f in files) / (1 << 20)
    throttle_bps = THROTTLE << 20
    out: dict = {
        "metric": "tune_bench", "smoke": SMOKE, "files": N_FILES,
        "total_mb": round(total_mb, 1),
        "throttle_mbps_per_conn": THROTTLE, "passes": PASSES,
    }
    try:
        # ---- the fixed sweep (tuner off: knobs pinned per config)
        fixed: dict = {}
        for name, streams, window in FIXED_CONFIGS:
            _reset_state()
            if streams is None:
                from demodel_tpu.utils.env import default_pull_window_mb

                streams = peer_mod._peer_streams()  # noqa: SLF001
                window = default_pull_window_mb() << 20
            knobs = SimpleNamespace(streams=streams, window_bytes=window)
            with ChaosPeer(node.url, _plan(),
                           throttle_bps=throttle_bps) as shim:
                secs, mbps, ok = _fetch_pass(shim.url, files, knobs)
            fixed[name] = {"streams": streams,
                           "window_mb": window >> 20,
                           "secs": round(secs, 3),
                           "mbps": round(mbps, 2), "bytes_exact": ok}
        out["fixed"] = fixed
        best = max(v["mbps"] for v in fixed.values())
        worst = min(v["mbps"] for v in fixed.values())
        out["best_fixed_mbps"] = best
        out["worst_fixed_mbps"] = worst

        # ---- the adaptive leg: knobs start at env defaults, the tuner
        # moves them from the live windowed signals over several passes.
        # A FRESH shim per pass replays the exact fault plan the fixed
        # legs faced — the comparison is symmetric, and the overall rate
        # sums pass transfer times only (shim setup stays off the clock,
        # as it does for the fixed legs).
        _reset_state()
        pass_mbps: list[float] = []
        pass_secs: list[float] = []
        adaptive_exact = True
        tuner = PullTuner(prefetch_depth=0).start()
        try:
            for _ in range(PASSES):
                with ChaosPeer(node.url, _plan(),
                               throttle_bps=throttle_bps) as shim:
                    secs, mbps, ok = _fetch_pass(shim.url, files, tuner)
                adaptive_exact = adaptive_exact and ok
                pass_mbps.append(round(mbps, 2))
                pass_secs.append(secs)
        finally:
            tuner.stop()
        overall = total_mb * PASSES / sum(pass_secs)
        converged = statistics.median(pass_mbps[-3:])
        out["adaptive"] = {
            "pass_mbps": pass_mbps,
            "overall_mbps": round(overall, 2),
            "converged_mbps": round(converged, 2),
            "bytes_exact": adaptive_exact,
            "decisions": tuner.decisions,
            "final_knobs": tuner.snapshot(),
        }
        # the tuner's own observability: decisions as span events in the
        # always-on flight recorder + tuner_* gauges on the scrape
        tuner_spans = [r for r in trace.recorder().snapshot()
                       if r["name"] == "tuner"]
        tune_events = [e for r in tuner_spans
                       for e in r.get("events", ())
                       if e["name"] == "tune"]
        out["adaptive"]["span_events"] = len(tune_events)
        out["adaptive"]["gauges"] = {
            k: v for k, v in m.HUB.gauges().items()
            if k.startswith("tuner_")}
        retry_total = sum(v for k, v in m.HUB.snapshot().items()
                          if k.startswith("peer_retries_total"))
        out["adaptive"]["retries"] = int(retry_total)
    finally:
        node.stop()

    conv_bound, worst_bound = (0.7, 0.9) if SMOKE else (0.9, 1.2)
    out["bounds"] = {"converged_vs_best": conv_bound,
                     "overall_vs_worst": worst_bound}
    out["tuner_ok"] = bool(
        all(v["bytes_exact"] for v in fixed.values())
        and adaptive_exact
        and out["adaptive"]["decisions"] > 0
        and out["adaptive"]["span_events"] > 0
        and "tuner_streams" in out["adaptive"]["gauges"]
        and converged >= conv_bound * best
        and overall >= worst_bound * worst)
    print(json.dumps(out))
    return 0 if out["tuner_ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
