"""Decode-throughput before/after for the flash kernel flip (VERDICT r4
next #2: "a decode-throughput before/after" is part of Done).

Runs Llama autoregressive decode twice — einsum cache attention vs the
fused flash kernel (`DEMODEL_FLASH_ATTN`) — on the CURRENT backend and
prints one JSON line with tok/s for both and the ratio. On the real chip
this is the number that justifies (or vetoes) the default flip; on CPU
it smoke-tests the harness (interpret-mode pallas is slow there by
construction, so the ratio only means something on TPU).

The two runs happen in SUBPROCESSES so each sees its env knob at import
time and neither inherits the other's compiled cache.

Usage: decode_bench.py [--tiny]
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _child() -> None:
    sys.path.insert(0, str(REPO))
    import jax

    if os.environ.get("DECODE_BENCH_CPU"):
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass
    import numpy as np

    from demodel_tpu.models.llama import (
        LlamaConfig, generate, init_params,
    )

    tiny = bool(os.environ.get("DECODE_BENCH_TINY"))
    cfg = LlamaConfig(
        vocab_size=256,
        hidden_size=64 if tiny else 1024,
        num_hidden_layers=2 if tiny else 8,
        num_attention_heads=4 if tiny else 16,
        num_key_value_heads=2 if tiny else 4,
        intermediate_size=128 if tiny else 2816,
    )
    params = init_params(jax.random.key(0), cfg)
    prompt = np.arange(32, dtype=np.int32)[None] % cfg.vocab_size
    new = 16 if tiny else 64
    # warmup with the SAME max_new_tokens: generate() sizes the KV cache
    # as T0 + max_new_tokens, so a different count means a different
    # static shape and a full recompile inside the timed region
    jax.block_until_ready(generate(params, cfg, prompt, new))
    t0 = time.time()
    out = generate(params, cfg, prompt, new)
    jax.block_until_ready(out)
    secs = time.time() - t0
    print(json.dumps({
        "flash": os.environ.get("DEMODEL_FLASH_ATTN", "") == "1",
        "backend": jax.default_backend(),
        "new_tokens": new,
        "decode_tok_per_s": round(new / secs, 2),
    }))


def main() -> int:
    if "--child" in sys.argv:
        _child()
        return 0
    env = dict(os.environ)
    if "--tiny" in sys.argv:
        env["DECODE_BENCH_TINY"] = "1"
    results = {}
    for flash in ("0", "1"):
        key = "flash" if flash == "1" else "einsum"
        e = dict(env)
        e["DEMODEL_FLASH_ATTN"] = flash
        # two attempts — but ONLY for the transient tunnel-transport
        # signatures ("Broken pipe" on remote_compile etc.): retrying a
        # deterministic failure would burn up to ~31 min of a scarce
        # live window per key for an identical error
        transient = ("broken pipe", "connection reset", "network error",
                     "transport", "unavailable")
        for attempt in (1, 2):
            try:
                r = subprocess.run([sys.executable, __file__, "--child"],
                                   env=e, capture_output=True, text=True,
                                   timeout=1800)
            except subprocess.TimeoutExpired:
                results[key] = {"error": "timeout after 1800s"}
                break
            lines = r.stdout.strip().splitlines()
            if r.returncode != 0 or not lines:
                err = (r.stderr or "no output")[-300:]
                results[key] = {"error": f"rc={r.returncode}: {err}"}
                if attempt == 1 and any(
                        s in (r.stderr or "").lower() for s in transient):
                    time.sleep(60)
                    continue
                break
            try:
                results[key] = json.loads(lines[-1])
            except ValueError:
                results[key] = {"error": (r.stderr or lines[-1])[-300:]}
            break
    ein = results.get("einsum", {}).get("decode_tok_per_s")
    fla = results.get("flash", {}).get("decode_tok_per_s")
    out = {"decode_before_after": results}
    if ein and fla:
        out["flash_speedup"] = round(fla / ein, 3)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
