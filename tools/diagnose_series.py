"""Read BENCH_SERIES_r05.jsonl and diagnose each rep: where did the
sharded leg's time go, and was the run channel-bound or code-bound?

Per rep with a parsed result, prints one line:

  ts  value  (whole-file / sharded MB/s)  fetch/place/block split
  link_sustained  → verdict

Verdicts:
- ``channel-bound``: the sharded rate is within 30% of the sustained
  link rate — the tunnel, not the delivery pipeline, set the ceiling;
- ``place-bound``: device placement wall dominates the split but sits
  well under the link rate — the pipeline's host→device path is the
  suspect (transfer granularity, sync points);
- ``fetch-bound``: network fetch wall dominates — peer/DCN side;
- ``inconclusive``: missing fields (pre-instrumentation reps).

Usage: python tools/diagnose_series.py [series.jsonl]
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def diagnose(parsed: dict) -> str:
    phases = parsed.get("sharded_phase_secs") or {}
    link = parsed.get("link_sustained_mbps")
    sharded = parsed.get("sharded_mbps")
    fetch = phases.get("fetch_secs", phases.get("fetch_stall_secs"))
    place = phases.get("place_secs")
    if sharded is None or place is None:
        return "inconclusive (pre-instrumentation rep)"
    if link and sharded >= 0.7 * link:
        return f"channel-bound (sharded {sharded} vs link {link} MB/s)"
    if fetch is not None and place > 2 * max(fetch, 1e-9):
        return (f"place-bound (place {place:.2f}s vs fetch {fetch:.2f}s"
                + (f"; link {link} MB/s" if link else "") + ")")
    if fetch is not None and fetch > 2 * place:
        return f"fetch-bound (fetch {fetch:.2f}s vs place {place:.2f}s)"
    return "mixed (no phase dominates 2:1)"


def main() -> int:
    path = Path(sys.argv[1]) if len(sys.argv) > 1 else \
        REPO / "BENCH_SERIES_r05.jsonl"
    for line in path.read_text().splitlines():
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        parsed = rec.get("parsed")
        if not isinstance(parsed, dict) or "value" not in parsed:
            continue
        phases = parsed.get("sharded_phase_secs") or {}
        print(f"{rec.get('ts', '?'):25s} {parsed['value']:>8} "
              f"{parsed.get('unit', '')}  "
              f"(file {parsed.get('whole_file_mbps', '?')} / "
              f"sharded {parsed.get('sharded_mbps', '?')})  "
              f"phases={json.dumps(phases) if phases else 'n/a'} "
              f"block={parsed.get('sharded_block_secs', 'n/a')} "
              f"→ {diagnose(parsed)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
