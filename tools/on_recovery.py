"""One-shot tunnel-recovery sequence (PROFILE_r04.md checklist).

Run the moment a probe reports ok:true:

1. one full `bench.py` (driver-comparable) — recorded immediately;
2. a flash-attention compile check on the real chip (the kernel is
   interpret-tested; this validates Mosaic lowering);
3. two more spaced bench reps via bench_series (the tunnel wedges under
   abuse, so reps are separated by a cool-down).

Everything appends to BENCH_SERIES_r05.jsonl / prints JSON lines; commit
the artifacts after.
"""

from __future__ import annotations

import json
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _flash_child() -> None:
    sys.path.insert(0, str(REPO))
    import os

    import numpy as np

    import jax

    if os.environ.get("FLASH_CHECK_TINY"):
        # CPU smoke of this script: env vars can't switch the backend (a
        # sitecustomize registers the TPU first) — only config can
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass
    import jax.numpy as jnp

    from demodel_tpu.ops.flash_attention import (
        flash_attention, reference_attention,
    )

    dt = jnp.bfloat16
    # chip shapes by default; FLASH_CHECK_TINY=1 keeps the CPU smoke of
    # this script itself fast (interpret mode executes grid steps in
    # Python — the real check runs on the TPU where the kernel compiles)
    S, H, G, D = (32, 2, 1, 32) if os.environ.get("FLASH_CHECK_TINY") \
        else (512, 8, 2, 128)
    ks = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(ks[0], (1, S, H, D), dt)
    k = jax.random.normal(ks[1], (1, S, G, D), dt)
    v = jax.random.normal(ks[2], (1, S, G, D), dt)
    t0 = time.time()
    out = flash_attention(q, k, v, causal=True)
    out.block_until_ready()
    compile_s = time.time() - t0
    t0 = time.time()
    out = flash_attention(q, k, v, causal=True)
    out.block_until_ready()
    run_s = time.time() - t0
    ref = reference_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                              v.astype(jnp.float32), causal=True)
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32) - ref)))
    ok = bool(err < 0.1 and np.isfinite(err))

    # the lse output path (the ring-attention consumer) lowers through a
    # different out_spec — validate it on-chip too, not just the plain
    # forward
    from demodel_tpu.ops.flash_attention import reference_attention_lse

    out2, lse = flash_attention(q, k, v, causal=True, return_lse=True)
    _, ref_lse = reference_attention_lse(
        q.astype(jnp.float32), k.astype(jnp.float32),
        v.astype(jnp.float32), causal=True)
    lse_err = float(jnp.max(jnp.abs(lse - ref_lse)))
    ok = ok and bool(lse_err < 0.05 and np.isfinite(lse_err))

    # ring attention compiles the flash tiles INSIDE shard_map (the
    # long-context flagship) — its own lowering, its own record flag, so
    # a ring-specific failure doesn't block the plain-forward flip
    # ring size = every chip present (ONE in this environment — the
    # multi-step rotation semantics are covered by the 8-device CPU
    # interpret suite; what only silicon can validate is the kernel's
    # Mosaic lowering inside shard_map, which is per-device identical at
    # any ring size). ring_devices in the record says what actually ran.
    ring_ok, ring_err = False, None
    ring_devices = jax.device_count()
    try:
        from demodel_tpu.ops.ring_attention import ring_attention_sharded
        from demodel_tpu.parallel.mesh import make_mesh

        os.environ["DEMODEL_FLASH_RING"] = "1"
        mesh = make_mesh(sp=ring_devices)
        r_out = ring_attention_sharded(q, k, v, mesh, axis="sp",
                                       causal=True)
        ring_err = float(jnp.max(jnp.abs(
            r_out.astype(jnp.float32) - ref)))
        ring_ok = bool(ring_err < 0.1 and np.isfinite(ring_err))
    except Exception as e:  # noqa: BLE001 — record the failure, don't die
        ring_err = f"{type(e).__name__}: {e}"[:300]
    finally:
        os.environ.pop("DEMODEL_FLASH_RING", None)

    # dequant kernels (ops/dequant.py) share the on-chip gate: same
    # Mosaic-lowering risk, same record. Oracle = the jnp math path the
    # kernels wrap (the CPU-delivery fallback, parity-tested in-suite).
    from demodel_tpu.ops import dequant as dq

    nb = 512  # blocks: multiple of the pallas tile
    rng = np.random.default_rng(0)
    d8 = jnp.asarray(rng.standard_normal(nb).astype(np.float16))
    qs8 = jnp.asarray(rng.integers(-127, 127, (nb, 32), dtype=np.int8))
    got8 = np.asarray(dq.dequant_q8_0(d8, qs8, jnp.float32))
    ref8 = np.asarray(dq._q8_0_math(d8, qs8, jnp.float32)).reshape(-1)
    err8 = float(np.max(np.abs(got8 - ref8)))
    d4 = jnp.asarray(rng.standard_normal(nb).astype(np.float16))
    qs4 = jnp.asarray(rng.integers(0, 255, (nb, 16), dtype=np.uint8))
    got4 = np.asarray(dq.dequant_q4_0(d4, qs4, jnp.float32))
    ref4 = np.asarray(dq._q4_0_math(d4, qs4, jnp.float32)).reshape(-1)
    err4 = float(np.max(np.abs(got4 - ref4)))
    dequant_ok = bool(err8 < 1e-2 and err4 < 1e-2
                      and np.isfinite(err8) and np.isfinite(err4))
    ok = ok and dequant_ok
    rec = {"flash_on_chip": True,
           "compile_s": round(compile_s, 1),
           "run_s": round(run_s, 4),
           "max_err_vs_ref": err,
           "lse_max_err": lse_err,
           "ring_ok": ring_ok,
           "ring_err": ring_err,
           "ring_devices": ring_devices,
           "dequant_max_err": {"q8_0": err8, "q4_0": err4},
           "backend": jax.default_backend(),
           "device": str(jax.devices()[0]),
           "ok": ok}
    print(json.dumps(rec))
    if ok and not os.environ.get("FLASH_CHECK_TINY") \
            and jax.default_backend() == "tpu":
        # the committed record that flips the flash defaults on for TPU
        # runs (demodel_tpu/ops/flash_default.py — VERDICT r4 #2)
        from demodel_tpu.ops.flash_default import ONCHIP_RECORD

        ONCHIP_RECORD.write_text(json.dumps(rec))


def main() -> int:
    if "--flash-child" in sys.argv:
        _flash_child()
        return 0
    print("[recovery] step 1: driver-comparable bench", file=sys.stderr)
    subprocess.run([sys.executable, str(REPO / "tools/bench_series.py"),
                    "1"], timeout=1800)
    print("[recovery] step 2: flash kernel on-chip compile check",
          file=sys.stderr)
    try:
        r = subprocess.run([sys.executable, __file__, "--flash-child"],
                           capture_output=True, text=True, timeout=600)
        print(r.stdout.strip() or r.stderr[-500:])
        with open(REPO / "BENCH_SERIES_r05.jsonl", "a") as f:
            f.write(json.dumps({"flash_check": r.stdout.strip()[-1500:]})
                    + "\n")
    except subprocess.TimeoutExpired:
        print('{"flash_on_chip": false, "error": "timeout"}')
    # bench reps BEFORE the decode leg: the scoreboard metric and the
    # kernel record are the round's deliverables, and live windows have
    # died at ~45 min — decode (two compiles + possible retries) must
    # not eat the reps' slot
    print("[recovery] step 3: two spaced bench reps", file=sys.stderr)
    for _ in range(2):
        time.sleep(120)  # cool-down: the tunnel wedges under abuse
        subprocess.run([sys.executable, str(REPO / "tools/bench_series.py"),
                        "1"], timeout=1800)
    print("[recovery] step 4: decode throughput before/after flash",
          file=sys.stderr)
    try:
        r = subprocess.run([sys.executable,
                            str(REPO / "tools/decode_bench.py")],
                           capture_output=True, text=True, timeout=3700)
        print(r.stdout.strip()[-500:] or r.stderr[-300:])
        lines = r.stdout.strip().splitlines()
        if r.returncode == 0 and lines:
            rec = lines[-1]
        else:
            rec = json.dumps({"decode_bench_error":
                              f"rc={r.returncode}: "
                              f"{(r.stderr or 'no output')[-300:]}"})
    except subprocess.TimeoutExpired:
        rec = json.dumps({"decode_bench_error": "timeout after 3700s"})
        print(rec)
    with open(REPO / "BENCH_SERIES_r05.jsonl", "a") as f:
        f.write(rec + "\n")

    # commit the captured artifacts (narrow pathspec: never sweeps
    # unrelated work-in-progress into an automated commit) — a window
    # that opens and closes unattended must still leave its evidence in
    # history
    try:
        artifacts = [p for p in (
            "BENCH_SERIES_r05.jsonl", "TUNNEL_LOG.jsonl",
            "demodel_tpu/ops/_flash_onchip_validated.json",
            ".recovery_fired_r05") if (REPO / p).exists()]
        subprocess.run(["git", "add", *artifacts], cwd=REPO, timeout=60)
        # --only + explicit pathspec: a bare `git commit -m` would sweep
        # whatever ELSE happened to be staged (a human's half-finished
        # work-in-progress) into this automated commit
        r = subprocess.run(
            ["git", "commit", "--only", "-m",
             "Record on-chip captures from recovered tunnel window\n\n"
             "Automated by tools/on_recovery.py: bench series reps, the\n"
             "kernel on-chip validation record, and the probe log.",
             "--", *artifacts],
            cwd=REPO, capture_output=True, text=True, timeout=60)
        print(f"[recovery] artifact commit: rc={r.returncode} "
              f"{(r.stdout or r.stderr)[-200:]}", file=sys.stderr)
    except Exception as e:  # noqa: BLE001 — capture must not die on git
        print(f"[recovery] artifact commit failed: {e}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
