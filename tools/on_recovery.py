"""One-shot tunnel-recovery sequence (PROFILE_r04.md checklist).

Run the moment a probe reports ok:true:

1. one full `bench.py` (driver-comparable) — recorded immediately;
2. a flash-attention compile check on the real chip (the kernel is
   interpret-tested; this validates Mosaic lowering);
3. two more spaced bench reps via bench_series (the tunnel wedges under
   abuse, so reps are separated by a cool-down).

Everything appends to BENCH_SERIES_r05.jsonl / prints JSON lines; commit
the artifacts after.
"""

from __future__ import annotations

import json
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _flash_child() -> None:
    sys.path.insert(0, str(REPO))
    import os

    import numpy as np

    import jax

    if os.environ.get("FLASH_CHECK_TINY"):
        # CPU smoke of this script: env vars can't switch the backend (a
        # sitecustomize registers the TPU first) — only config can
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass
    import jax.numpy as jnp

    from demodel_tpu.ops.flash_attention import (
        flash_attention, reference_attention,
    )

    dt = jnp.bfloat16
    # chip shapes by default; FLASH_CHECK_TINY=1 keeps the CPU smoke of
    # this script itself fast (interpret mode executes grid steps in
    # Python — the real check runs on the TPU where the kernel compiles)
    S, H, G, D = (32, 2, 1, 32) if os.environ.get("FLASH_CHECK_TINY") \
        else (512, 8, 2, 128)
    ks = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(ks[0], (1, S, H, D), dt)
    k = jax.random.normal(ks[1], (1, S, G, D), dt)
    v = jax.random.normal(ks[2], (1, S, G, D), dt)
    t0 = time.time()
    out = flash_attention(q, k, v, causal=True)
    out.block_until_ready()
    compile_s = time.time() - t0
    t0 = time.time()
    out = flash_attention(q, k, v, causal=True)
    out.block_until_ready()
    run_s = time.time() - t0
    ref = reference_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                              v.astype(jnp.float32), causal=True)
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32) - ref)))
    print(json.dumps({"flash_on_chip": True,
                      "compile_s": round(compile_s, 1),
                      "run_s": round(run_s, 4),
                      "max_err_vs_ref": err,
                      "ok": bool(err < 0.1 and np.isfinite(err))}))


def main() -> int:
    if "--flash-child" in sys.argv:
        _flash_child()
        return 0
    print("[recovery] step 1: driver-comparable bench", file=sys.stderr)
    subprocess.run([sys.executable, str(REPO / "tools/bench_series.py"),
                    "1"], timeout=1800)
    print("[recovery] step 2: flash kernel on-chip compile check",
          file=sys.stderr)
    try:
        r = subprocess.run([sys.executable, __file__, "--flash-child"],
                           capture_output=True, text=True, timeout=600)
        print(r.stdout.strip() or r.stderr[-500:])
        with open(REPO / "BENCH_SERIES_r05.jsonl", "a") as f:
            f.write(json.dumps({"flash_check": r.stdout.strip()[-1500:]})
                    + "\n")
    except subprocess.TimeoutExpired:
        print('{"flash_on_chip": false, "error": "timeout"}')
    print("[recovery] step 3: two spaced bench reps", file=sys.stderr)
    for _ in range(2):
        time.sleep(120)  # cool-down: the tunnel wedges under abuse
        subprocess.run([sys.executable, str(REPO / "tools/bench_series.py"),
                        "1"], timeout=1800)
    return 0


if __name__ == "__main__":
    sys.exit(main())
