#!/usr/bin/env python
"""Flame report over profiler captures — ONE JSON line.

Reads captures from the continuous profiling plane
(:mod:`demodel_tpu.utils.profiler` and the native ``/debug/profile``
twin) in any of three shapes:

- **JSON captures** (``/debug/profile`` default output, or an archived
  window record): ``{"stacks": [{"stack": "a;b;c", "wall": N, "cpu": N}]}``;
- **collapsed text** (``format=collapsed``): ``a;b;c COUNT`` lines,
  ready for external flame-graph tooling;
- **archive directories** (``DEMODEL_TELEMETRY_ARCHIVE``): every
  ``kind=profile`` window record the retention plane flushed, merged —
  spanning node restarts, because the archive does.

The report gives top-N frames by *self* (leaf) and *total* (anywhere on
the stack) time plus the per-span breakdown the trace join enables: the
root segment of a Python-plane stack is the innermost active span
(``window-read``, ``place``, …), of a native stack the serve thread.

``--diff BASELINE`` renders the flame diff against an earlier capture
and exits **rc=1** when any frame's share of samples grew by at least
``--threshold`` (default 0.05 — five share points): the gate that makes
a bench regression attributable to a frame, not just a number.
``--validate`` is the parse-only CI smoke gate, same contract as
``telemetry_report.py``.

Usage::

    python tools/profile_report.py prof.json
    python tools/profile_report.py after.collapsed --diff before.collapsed
    python tools/profile_report.py /var/tmp/telemetry-archive --plane python
    python tools/profile_report.py prof.json --validate
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def _merge(agg: dict[str, list[float]], stack: str, wall: float,
           cpu: float) -> None:
    row = agg.setdefault(stack, [0.0, 0.0])
    row[0] += wall
    row[1] += cpu


def _load_json_doc(doc: dict, agg: dict[str, list[float]]) -> int:
    n = 0
    for row in doc.get("stacks") or []:
        stack = row.get("stack")
        if not stack:
            continue
        _merge(agg, str(stack), float(row.get("wall") or 0.0),
               float(row.get("cpu") or 0.0))
        n += 1
    return n


def load(path: Path, plane: str | None = None) -> dict[str, list[float]]:
    """``{stack: [wall, cpu]}`` of one capture file or archive dir.

    A missing path is fatal — the smoke gate's whole point is "the
    capture exists and parses".
    """
    agg: dict[str, list[float]] = {}
    path = Path(path)
    if path.is_dir():
        from demodel_tpu.utils.retention import TelemetryArchive
        for rec in TelemetryArchive(path).profiles(plane=plane):
            _load_json_doc(rec, agg)
        return agg
    if not path.is_file():
        raise SystemExit(f"{path}: no such capture file or archive")
    text = path.read_text()
    if text.lstrip().startswith("{"):
        _load_json_doc(json.loads(text), agg)
        return agg
    # collapsed: "seg;seg;seg COUNT" per line (wall counts only)
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        stack, _, count = line.rpartition(" ")
        if not stack:
            continue
        try:
            _merge(agg, stack, float(count), 0.0)
        except ValueError:
            continue
    return agg


def _frames(agg: dict[str, list[float]]) -> dict[str, dict[str, float]]:
    """Per-frame self/total/cpu rollup over the folded stacks."""
    out: dict[str, dict[str, float]] = {}
    for stack, (wall, cpu) in agg.items():
        segs = stack.split(";")
        for seg in set(segs):  # count a frame once per stack, not per repeat
            row = out.setdefault(seg, {"self": 0.0, "total": 0.0, "cpu": 0.0})
            row["total"] += wall
            row["cpu"] += cpu
        out[segs[-1]]["self"] += wall
    return out


def _spans(agg: dict[str, list[float]]) -> dict[str, dict[str, float]]:
    """Root-segment breakdown: the span join for Python-plane stacks
    (span names carry no ``:``), the serve thread for native ones."""
    out: dict[str, dict[str, float]] = {}
    for stack, (wall, cpu) in agg.items():
        root = stack.split(";", 1)[0]
        if ":" in root or root == "-":
            root = "(unattributed)"  # "-" is the profiler's no-span root
        row = out.setdefault(root, {"wall": 0.0, "cpu": 0.0})
        row["wall"] += wall
        row["cpu"] += cpu
    return out


def report(agg: dict[str, list[float]], top: int = 10) -> dict:
    total = sum(w for w, _ in agg.values())
    frames = _frames(agg)

    def rank(key: str) -> list[dict]:
        rows = sorted(frames.items(), key=lambda kv: (-kv[1][key], kv[0]))
        return [{"frame": f, "self": round(r["self"], 3),
                 "total": round(r["total"], 3),
                 "share": round(r[key] / total, 4) if total else 0.0}
                for f, r in rows[:top] if r[key] > 0]

    spans = {
        name: {"wall": round(r["wall"], 3), "cpu": round(r["cpu"], 3),
               "share": round(r["wall"] / total, 4) if total else 0.0}
        for name, r in sorted(_spans(agg).items(),
                              key=lambda kv: -kv[1]["wall"])
    }
    return {
        "metric": "profile_report",
        "samples": round(total, 3),
        "stacks": len(agg),
        "top_self": rank("self"),
        "top_total": rank("total"),
        "spans": spans,
    }


def diff(after: dict[str, list[float]], before: dict[str, list[float]],
         threshold: float, top: int = 10) -> tuple[dict, int]:
    """Flame diff by per-frame sample share; rc=1 on regression.

    Shares (frame total / capture total) rather than raw counts, so two
    captures of different lengths or rates compare honestly.
    """
    a_total = sum(w for w, _ in after.values()) or 1.0
    b_total = sum(w for w, _ in before.values()) or 1.0
    a_frames = _frames(after)
    b_frames = _frames(before)
    deltas = []
    for frame in set(a_frames) | set(b_frames):
        a_share = a_frames.get(frame, {}).get("total", 0.0) / a_total
        b_share = b_frames.get(frame, {}).get("total", 0.0) / b_total
        d = a_share - b_share
        if abs(d) < 1e-9:
            continue
        deltas.append({"frame": frame, "before": round(b_share, 4),
                       "after": round(a_share, 4), "delta": round(d, 4)})
    deltas.sort(key=lambda r: (-r["delta"], r["frame"]))
    regressions = [r for r in deltas if r["delta"] >= threshold]
    out = {
        "metric": "profile_diff",
        "samples": [round(b_total, 3), round(a_total, 3)],
        "threshold": threshold,
        "regressions": regressions[:top],
        "grown": deltas[:top],
        "shrunk": list(reversed(deltas[-top:])),
        "ok": not regressions,
    }
    return out, (1 if regressions else 0)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("capture", type=Path,
                    help="profile capture (json or collapsed) or "
                         "telemetry archive directory")
    ap.add_argument("--diff", type=Path, metavar="BASELINE",
                    help="flame-diff against this earlier capture; "
                         "rc=1 when a frame's share grew >= threshold")
    ap.add_argument("--threshold", type=float, default=0.05,
                    metavar="SHARE",
                    help="regression gate for --diff, in share of total "
                         "samples (default 0.05)")
    ap.add_argument("--top", type=int, default=10, metavar="N",
                    help="rows per ranking (default 10)")
    ap.add_argument("--plane", choices=("python", "native"),
                    help="archive dirs only: keep one plane's windows")
    ap.add_argument("--validate", action="store_true",
                    help="parse gate only (CI smoke); nonzero unless at "
                         "least one stack decodes")
    args = ap.parse_args(argv)

    agg = load(args.capture, plane=args.plane)
    if args.validate:
        if not agg:
            raise SystemExit(f"{args.capture}: no profile stacks decoded")
        print(json.dumps({"metric": "profile_report_validate", "ok": True,
                          "stacks": len(agg)}))
        return 0
    if not agg:
        raise SystemExit(f"{args.capture}: empty capture")
    if args.diff is not None:
        base = load(args.diff, plane=args.plane)
        if not base:
            raise SystemExit(f"{args.diff}: empty baseline capture")
        out, rc = diff(agg, base, threshold=args.threshold, top=args.top)
        print(json.dumps(out))
        return rc
    print(json.dumps(report(agg, top=args.top)))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
