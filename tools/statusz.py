#!/usr/bin/env python
"""Render a live ``/debug/statusz`` endpoint or a flight-recorder dump as
ONE JSON line (the ``bench.py`` / ``trace_report.py`` contract).

Sources, auto-detected:

- ``http://host:port`` (or a full ``.../debug/statusz`` URL) — the live
  endpoint of a Python restore server or the native proxy;
- a ``demodel-flightrec-*.json`` file — the post-mortem the flight
  recorder dumped on SIGUSR2 / an error-status root span.

The report leads with what an operator triages first: open breakers, the
oldest in-flight spans (a stuck pull shows as a ``window-read`` with a
large ``age_sec``), budget pressure, and — for recorder dumps — the
per-stage breakdown + error spans of the captured ring.

``--validate`` exits nonzero unless the source parses AND carries the
statusz/recorder schema — the CI statusz-smoke gate.

``--fleet host1,host2,...`` renders the POD view: one JSON line joining
every host's statusz — per-host open breakers, swarm chunk progress, and
the oldest in-flight span — the "which host is the slow one" answer for
a pod-scale swarm pull, one command instead of N curls.

``--fleet ... --watch SECS`` turns the pod view into a TIME SERIES: every
interval it polls each host's ``/debug/telemetry`` (the sliding-window
rate/p99 surface both planes serve) and emits one JSONL line — the
continuous pod view a long swarm pull needs (pipe to a file, live-tail
it, or feed it to a plotter). ``--samples N`` bounds the loop (CI and
scripting); default runs until interrupted.

Usage::

    python tools/statusz.py http://127.0.0.1:8800
    python tools/statusz.py /tmp/demodel-flightrec-4242-1.json
    python tools/statusz.py http://127.0.0.1:8800 --validate
    python tools/statusz.py --fleet host-a:8800,host-b:8800,host-c:8800
    python tools/statusz.py --fleet host-a:8800,host-b:8800 --watch 5
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from demodel_tpu.utils.trace import nest_spans  # noqa: E402
from tools.trace_report import stage_breakdown  # noqa: E402


def load(source: str) -> tuple[dict, str]:
    if source.startswith(("http://", "https://")):
        url = source
        if "/debug/" not in url:  # bare host:port → the statusz document
            url = url.rstrip("/") + "/debug/statusz"
        with urllib.request.urlopen(url, timeout=10) as r:
            return json.loads(r.read()), url
    return json.loads(Path(source).read_text(encoding="utf-8")), source


def _flatten_inflight(tree: list[dict], depth: int = 0) -> list[dict]:
    out = []
    for node in tree:
        entry = {"name": node.get("name"), "age_sec": node.get("age_sec"),
                 "depth": depth}
        if node.get("attrs"):
            entry["attrs"] = node["attrs"]
        out.append(entry)
        out.extend(_flatten_inflight(node.get("children", []), depth + 1))
    return out


def report(doc: dict, source: str) -> dict:
    out: dict = {"metric": "statusz_report", "source": source}
    if doc.get("kind") == "demodel-flight-recorder":
        spans = doc.get("spans", [])
        out.update({
            "kind": "flight-recorder",
            "reason": doc.get("reason"),
            "pid": doc.get("pid"),
            "spans": len(spans),
            "dropped": doc.get("dropped", 0),
            "errors": [
                {"name": r["name"], "error": r.get("error", ""),
                 "secs": r.get("dur", 0.0)}
                for r in spans if r.get("status") == "error"],
            "stages": stage_breakdown(spans),
            "inflight": _flatten_inflight(
                nest_spans(doc.get("inflight", []))),
        })
        return out
    if "statusz" not in doc:
        raise SystemExit(f"{source}: neither a statusz document nor a "
                         "flight-recorder dump")
    out["kind"] = "statusz"
    out["server"] = doc.get("server")
    out["uptime_sec"] = doc.get("uptime_sec")
    breakers = doc.get("breakers", {})
    out["breakers_open"] = [
        {"peer": peer, **b} for peer, b in sorted(breakers.items())
        if b.get("state") != "closed"]
    out["breakers_total"] = len(breakers)
    out["inflight"] = _flatten_inflight(doc.get("inflight_spans", []))
    budgets = doc.get("budgets", [])
    if budgets:
        out["budgets"] = budgets
    if "conns" in doc:  # the native proxy's section
        out["conns"] = doc["conns"]
    if "trace" in doc:
        out["trace"] = doc["trace"]
    return out


def _oldest_inflight(flat: list[dict]) -> dict | None:
    with_age = [e for e in flat if isinstance(e.get("age_sec"), (int, float))]
    if not with_age:
        return None
    top = max(with_age, key=lambda e: e["age_sec"])
    return {"name": top.get("name"), "age_sec": top.get("age_sec")}


def _peer_rows(doc: dict) -> list[dict]:
    """Per-peer attribution for one host: join breaker states with the
    per-peer windowed counter rates the statusz telemetry slice carries
    (labels intact) — "which peer is retrying/faulting" in one table
    instead of a family-aggregated number."""
    from demodel_tpu.utils.metrics import parse_labels

    by_peer: dict[str, dict] = {}
    rates = (doc.get("telemetry") or {}).get("rates", {})
    for name, windows in rates.items():
        base, labels = parse_labels(name)
        peer = labels.get("peer")
        if peer is None:
            continue
        row = by_peer.setdefault(peer, {"peer": peer})
        rate_30 = (windows or {}).get("30")
        if base == "peer_retries_total":
            row["retry_rate_30s"] = rate_30
        else:
            row.setdefault("rates_30s", {})[base] = rate_30
    for peer, b in (doc.get("breakers") or {}).items():
        by_peer.setdefault(peer, {"peer": peer})["breaker"] = b.get("state")
    return [by_peer[p] for p in sorted(by_peer)]


def fleet_report(hosts: list[str]) -> dict:
    """The pod view: every host's statusz joined into one line. A host
    that doesn't answer is reported, not fatal — the dead host is
    usually the finding."""
    out: dict = {"metric": "statusz_fleet", "hosts": [], "unreachable": []}
    swarm_total = swarm_have = 0
    for host in hosts:
        source = host if host.startswith(("http://", "https://")) \
            else f"http://{host}"
        try:
            doc, _url = load(source)
        except Exception as e:  # noqa: BLE001 — per-host degrade is the point
            out["unreachable"].append({"host": host, "error": str(e)})
            continue
        breakers = doc.get("breakers", {})
        entry: dict = {
            "host": host,
            "server": doc.get("server"),
            "uptime_sec": doc.get("uptime_sec"),
            "breakers_open": [
                {"peer": peer, **b} for peer, b in sorted(breakers.items())
                if b.get("state") != "closed"],
            "swarm": doc.get("swarm", []),
            "oldest_inflight": _oldest_inflight(
                _flatten_inflight(doc.get("inflight_spans", []))),
        }
        peers = _peer_rows(doc)
        if peers:
            entry["peers"] = peers
        for b in entry["swarm"]:
            swarm_total += int(b.get("chunks_total", 0))
            swarm_have += int(b.get("chunks_have", 0))
        if "conns" in doc:  # native proxy hosts
            entry["conns"] = doc["conns"]
        out["hosts"].append(entry)
    out["hosts_up"] = len(out["hosts"])
    out["hosts_down"] = len(out["unreachable"])
    out["breakers_open_total"] = sum(
        len(h["breakers_open"]) for h in out["hosts"])
    if swarm_total:
        out["swarm_progress"] = {
            "chunks_have": swarm_have, "chunks_total": swarm_total,
            "pct": round(100.0 * swarm_have / swarm_total, 1)}
    return out


def validate(doc: dict, source: str) -> None:
    """Schema gate for CI: the fields every consumer of this surface
    depends on must exist with the right shapes."""
    if doc.get("kind") == "demodel-flight-recorder":
        for key in ("reason", "ts", "pid", "spans", "inflight"):
            if key not in doc:
                raise SystemExit(f"{source}: recorder dump missing {key!r}")
        return
    if doc.get("telemetry") == 1:
        # the time-series document (Python or native plane)
        if not isinstance(doc.get("windows"), dict):
            raise SystemExit(f"{source}: telemetry missing 'windows'")
        native = doc.get("server") == "demodel-native-proxy"
        if not native and "windows_s" not in doc.get("windows", {}):
            raise SystemExit(f"{source}: telemetry missing windows_s")
        return
    version = doc.get("statusz")
    if version not in (1, 2, 3, 4):
        raise SystemExit(f"{source}: missing/unknown statusz schema version")
    native = doc.get("server") == "demodel-native-proxy"
    required = (("config", "conns", "metrics") if native else
                ("breakers", "budgets", "inflight_spans", "trace",
                 "swarm", "config", "telemetry"))
    for key in required:
        if key not in doc:
            raise SystemExit(f"{source}: statusz missing {key!r}")
    if version >= 2 and "tiers" not in doc:
        # v2 promise on BOTH planes: tier occupancy/budget is reportable
        # (null on a native proxy running without a store)
        raise SystemExit(f"{source}: statusz v2 missing 'tiers'")
    if version >= 3 and "storage" not in doc:
        # v3 promise on BOTH planes: degraded-mode/quarantine/scrub state
        # is reportable (empty on a node that holds no store)
        raise SystemExit(f"{source}: statusz v3 missing 'storage'")
    if version >= 4 and not native:
        # v4 promise: the token-serving plane is reportable (empty on a
        # node that never booted a generation engine)
        if "generation" not in doc:
            raise SystemExit(f"{source}: statusz v4 missing 'generation'")
        gen = doc["generation"]
        if gen and not ("kv" in gen and "running" in gen):
            raise SystemExit(
                f"{source}: generation section missing kv/running")
    if native and "hist" not in doc["metrics"]:
        raise SystemExit(f"{source}: native metrics missing histograms")
    if native:
        # the zero-copy writer plane's vitals (EPOLLOUT writer + splice
        # tunnels) — consumers size slow-client eviction off these
        writer = doc.get("writer")
        if not isinstance(writer, dict):
            raise SystemExit(f"{source}: native statusz missing 'writer'")
        for key in ("conns_writing", "tunnels_spliced", "write_timeout_sec",
                    "write_min_bps", "ktls", "stall_evictions",
                    "sendfile_bytes", "splice_bytes"):
            if key not in writer:
                raise SystemExit(f"{source}: writer section missing {key!r}")
    if not native:
        for knob in doc["config"].values():
            if not (isinstance(knob, dict) and "value" in knob
                    and knob.get("source") in ("env", "default", "tuner")):
                raise SystemExit(f"{source}: malformed config knob {knob!r}")


def _telemetry_url(host: str) -> str:
    base = host if host.startswith(("http://", "https://")) \
        else f"http://{host}"
    return base.rstrip("/") + "/debug/telemetry"


def _host_telemetry_entry(host: str, doc: dict) -> dict:
    """One host's row in a watch sample: the key windowed series an
    operator tails — per-family p99s + rates, both planes."""
    entry: dict = {"host": host, "server": doc.get("server")}
    windows = doc.get("windows")
    if isinstance(windows, dict) and "hist" in windows:
        # Python plane: the hub summary (+ the native mirror when nested)
        entry["snapshots"] = windows.get("snapshots")
        entry["p99_30s"] = {
            name: fam.get("30", {}).get("p99")
            for name, fam in windows.get("hist", {}).items()}
        entry["rate_30s"] = {
            name: fam.get("30")
            for name, fam in windows.get("rates", {}).items()}
        native = doc.get("native")
        if isinstance(native, dict):
            entry["native_p99_30s"] = {
                name: fam.get("30", {}).get("p99")
                for name, fam in native.get("hist", {}).items()}
    elif isinstance(windows, dict):
        # native plane: windows["30"][family][route]
        entry["snapshots"] = doc.get("snapshots")
        entry["p99_30s"] = {
            f"{family}{{route={route}}}": spec.get("p99")
            for family, routes in windows.get("30", {}).items()
            for route, spec in routes.items()}
        entry["rate_30s"] = {
            f"{family}{{route={route}}}": spec.get("rate")
            for family, routes in windows.get("30", {}).items()
            for route, spec in routes.items()}
    return entry


def _poll_host(host: str) -> tuple[str, dict | None, str | None]:
    try:
        with urllib.request.urlopen(_telemetry_url(host), timeout=10) as r:
            return host, json.loads(r.read()), None
    except Exception as e:  # noqa: BLE001 — per-host degrade
        return host, None, str(e)


def watch_fleet(hosts: list[str], interval_s: float,
                samples: int | None = None, out=None,
                ship: str | None = None) -> int:
    """Poll every host's ``/debug/telemetry`` each interval and emit one
    JSONL line per tick — the continuous pod time series. The polling
    itself drives each node's snapshot ring, so the windows sharpen as
    the watch runs. Hosts are polled CONCURRENTLY and the sleep subtracts
    the poll time: one unreachable host (10 s connect timeout) must not
    stall the whole tick or starve the other hosts' sampling cadence."""
    import time as _time
    from concurrent.futures import ThreadPoolExecutor

    out = out if out is not None else sys.stdout
    archive = None
    if ship:
        # the fleet retention story: every tick also lands in a pod-level
        # TelemetryArchive (gzipped JSONL segments, node retention
        # budgets apply), which tools/telemetry_report.py renders later
        from demodel_tpu.utils.retention import TelemetryArchive

        archive = TelemetryArchive(Path(ship))
    n = 0
    try:
        with ThreadPoolExecutor(max_workers=min(32, max(1, len(hosts)))) as ex:
            while samples is None or n < samples:
                t0 = _time.monotonic()
                tick: dict = {"metric": "telemetry_fleet", "ts": _time.time(),
                              "interval_s": interval_s, "hosts": [],
                              "unreachable": []}
                for host, doc, err in ex.map(_poll_host, hosts):
                    if doc is not None:
                        tick["hosts"].append(_host_telemetry_entry(host, doc))
                    else:
                        tick["unreachable"].append({"host": host,
                                                    "error": err})
                print(json.dumps(tick, default=str), file=out, flush=True)
                if archive is not None:
                    archive.append(tick)
                n += 1
                if samples is None or n < samples:
                    _time.sleep(max(0.0, interval_s
                                    - (_time.monotonic() - t0)))
    finally:
        if archive is not None:
            archive.close()
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("source", nargs="?",
                    help="statusz URL (http://host:port) or "
                         "flight-recorder dump path")
    ap.add_argument("--validate", action="store_true",
                    help="schema-check only (CI smoke); nonzero on failure")
    ap.add_argument("--fleet", metavar="HOSTS",
                    help="comma-separated host[:port] list — render the "
                         "one-line pod view instead of a single source")
    ap.add_argument("--watch", metavar="SECS", type=float,
                    help="with --fleet: poll /debug/telemetry every SECS "
                         "and emit a JSONL time series")
    ap.add_argument("--samples", metavar="N", type=int,
                    help="with --watch: stop after N samples "
                         "(default: run until interrupted)")
    ap.add_argument("--ship", metavar="DIR",
                    help="with --fleet --watch: also append every tick "
                         "into a pod-level telemetry archive at DIR "
                         "(render with tools/telemetry_report.py)")
    args = ap.parse_args(argv)

    if args.watch is not None and args.watch <= 0:
        ap.error("--watch needs a positive interval")
    if args.ship and args.watch is None:
        ap.error("--ship requires --fleet --watch")
    if args.fleet:
        hosts = [h.strip() for h in args.fleet.split(",") if h.strip()]
        if not hosts:
            ap.error("--fleet needs at least one host")
        if args.watch is not None:
            return watch_fleet(hosts, args.watch, args.samples,
                               ship=args.ship)
        print(json.dumps(fleet_report(hosts), default=str))
        return 0
    if args.watch is not None:
        ap.error("--watch requires --fleet")
    if not args.source:
        ap.error("a source (or --fleet) is required")

    doc, source = load(args.source)
    if args.validate:
        validate(doc, source)
        print(json.dumps({"metric": "statusz_validate", "source": source,
                          "ok": True}))
        return 0
    print(json.dumps(report(doc, source), default=str))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
