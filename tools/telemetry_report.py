#!/usr/bin/env python
"""Trajectory report over telemetry archives — ONE JSON line.

Reads one or many ``DEMODEL_TELEMETRY_ARCHIVE`` directories (or single
``telemetry-*.jsonl.gz`` segments) written by the retention plane
(:mod:`demodel_tpu.utils.retention`) and renders the per-stage envelope
over wall-clock: for every family, the rate (counters), windowed p99
(histograms), and last value (gauges) across every archived window —
spanning node restarts, because the archive does.

Two record shapes are understood:

- node **window records** (the background flusher's output: counter
  deltas / gauge lasts / histogram bucket deltas per freshen window);
- shipped **fleet ticks** (``tools/statusz.py --fleet --watch --ship``):
  each host's 30 s rates/p99s land as ``family@host`` series.

Same one-JSON-line contract as ``bench.py`` / ``trace_report.py`` /
``statusz.py`` so drivers can scrape it. ``--validate`` exits nonzero
unless at least one record parses — the CI retention-smoke gate.

Usage::

    python tools/telemetry_report.py /var/tmp/telemetry-archive
    python tools/telemetry_report.py nodeA-archive nodeB-archive \\
        --family pull_bytes_total
    python tools/telemetry_report.py /tmp/pod-archive --validate
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from demodel_tpu.utils.metrics import hist_quantile  # noqa: E402
from demodel_tpu.utils.retention import (  # noqa: E402
    TelemetryArchive,
    read_segment,
)


def load_archive(path: Path) -> list[dict]:
    """Records of one archive directory (all segments, oldest first) or
    one bare segment file. A missing path is fatal — the smoke gate's
    whole point is "the archive exists and parses"."""
    p = Path(path)
    if p.is_dir():
        return TelemetryArchive(p).records()
    if p.is_file():
        return read_segment(p)
    raise SystemExit(f"{path}: no such archive directory or segment")


def _family_of(key: str) -> str:
    """Base family of a series key: strips labels and the ``@host``
    suffix fleet ticks add."""
    return key.partition("@")[0].partition("{")[0]


def _envelope(points: list[tuple[float, float]]) -> dict:
    vals = [v for _, v in points]
    return {
        "points": len(vals),
        "max": round(max(vals), 6),
        "avg": round(sum(vals) / len(vals), 6),
        "last": round(vals[-1], 6),
    }


def report(records: list[dict], family: str | None = None,
           since: float | None = None,
           until: float | None = None) -> dict:
    rate_pts: dict[str, list[tuple[float, float]]] = {}
    p99_pts: dict[str, list[tuple[float, float]]] = {}
    value_pts: dict[str, list[tuple[float, float]]] = {}
    walls: list[float] = []
    pids: set[int] = set()
    sources: set[str] = set()
    hosts: set[str] = set()
    used = skipped = 0

    def keep(book: dict, key: str, ts: float, value) -> None:
        if value is None:
            return
        if family is not None and _family_of(key) != family:
            return
        book.setdefault(key, []).append((ts, float(value)))

    for rec in sorted(records, key=lambda r: r.get("ts") or 0.0):
        ts = rec.get("ts")
        if not isinstance(ts, (int, float)):
            skipped += 1
            continue
        if (since is not None and ts < since) \
                or (until is not None and ts > until):
            continue
        if rec.get("metric") == "telemetry_fleet":
            # a shipped fleet tick: per-host 30 s windowed views
            used += 1
            walls.append(float(ts))
            for h in rec.get("hosts", []):
                host = h.get("host", "?")
                hosts.add(host)
                for name, value in (h.get("rate_30s") or {}).items():
                    keep(rate_pts, f"{name}@{host}", float(ts), value)
                for name, value in (h.get("p99_30s") or {}).items():
                    keep(p99_pts, f"{name}@{host}", float(ts), value)
            continue
        if not any(k in rec for k in ("counters", "gauges", "hists")):
            skipped += 1
            continue
        used += 1
        walls.append(float(ts))
        if isinstance(rec.get("pid"), int):
            pids.add(rec["pid"])
        if rec.get("source"):
            sources.add(str(rec["source"]))
        elapsed = float(rec.get("elapsed_s") or 0.0)
        for name, delta in (rec.get("counters") or {}).items():
            if elapsed > 0:
                keep(rate_pts, name, float(ts), float(delta) / elapsed)
        for name, value in (rec.get("gauges") or {}).items():
            keep(value_pts, name, float(ts), value)
        for name, h in (rec.get("hists") or {}).items():
            le = [float(b) for b in h.get("le", ())]
            counts = [int(c) for c in h.get("counts", ())]
            if sum(counts):
                keep(p99_pts, name, float(ts),
                     hist_quantile(le, counts, 0.99))

    families: dict[str, dict] = {}
    for book, kind in ((rate_pts, "rate"), (p99_pts, "p99"),
                       (value_pts, "value")):
        for name in sorted(book):
            families.setdefault(name, {})[kind] = _envelope(
                sorted(book[name]))
    out: dict = {
        "metric": "telemetry_report",
        "records": used,
        "skipped": skipped,
        "incarnations": len(pids),
        "families": families,
    }
    if walls:
        out["wall"] = [round(min(walls), 3), round(max(walls), 3)]
        out["span_s"] = round(max(walls) - min(walls), 3)
    if sources:
        out["sources"] = sorted(sources)
    if hosts:
        out["hosts"] = sorted(hosts)
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("archives", nargs="+", type=Path,
                    help="telemetry archive directories (or single "
                         "segment files)")
    ap.add_argument("--family", metavar="NAME",
                    help="report only this base family")
    ap.add_argument("--since", type=float, metavar="EPOCH",
                    help="drop windows before this wall-clock time")
    ap.add_argument("--until", type=float, metavar="EPOCH",
                    help="drop windows after this wall-clock time")
    ap.add_argument("--validate", action="store_true",
                    help="parse gate only (CI smoke); nonzero unless at "
                         "least one record parses")
    args = ap.parse_args(argv)

    records: list[dict] = []
    for path in args.archives:
        records.extend(load_archive(path))
    if args.validate:
        if not records:
            raise SystemExit(
                f"{', '.join(map(str, args.archives))}: no telemetry "
                "records decoded")
        print(json.dumps({"metric": "telemetry_report_validate",
                          "ok": True, "records": len(records),
                          "archives": len(args.archives)}))
        return 0
    if not records:
        raise SystemExit(
            f"{', '.join(map(str, args.archives))}: empty archive")
    out = report(records, family=args.family, since=args.since,
                 until=args.until)
    out["archives"] = len(args.archives)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
