#!/usr/bin/env python3
"""clang-tidy baseline gate for the native data plane.

``make -C native analyze`` runs this instead of raw clang-tidy: findings
are normalized to ``(file, check)`` counts and compared against the
checked-in baseline (``native/tidy_baseline.json``). Any NEW finding —
a (file, check) pair absent from the baseline, or a count above its
baselined value — fails the gate, so the native tree can only get
cleaner. Shrinking counts are reported (run ``--update`` to ratchet the
baseline down).

Usage (cwd = native/):
    python3 ../tools/tidy_gate.py store.cc proxy.cc selftest.cc
    python3 ../tools/tidy_gate.py --update store.cc proxy.cc selftest.cc
"""

from __future__ import annotations

import argparse
import json
import re
import shutil
import subprocess
import sys
from collections import Counter
from pathlib import Path

FINDING_RE = re.compile(
    r"^(?P<path>[^:\s][^:]*):(?P<line>\d+):(?P<col>\d+): "
    r"(?:warning|error): (?P<msg>.*?) \[(?P<checks>[^\]]+)\]$")

BASELINE = Path("tidy_baseline.json")


def run_tidy(sources: list[str], extra_cc_flags: list[str]) -> str:
    cmd = ["clang-tidy", "--quiet", *sources, "--",
           "-std=c++17", "-x", "c++", "-I.", *extra_cc_flags]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    # clang-tidy exits non-zero when WarningsAsErrors fire; the gate's
    # own baseline comparison decides pass/fail, so only a hard launch
    # failure (no output at all, rc != 0) is fatal here
    if proc.returncode != 0 and not proc.stdout.strip():
        print(proc.stderr, file=sys.stderr)
        raise SystemExit(f"clang-tidy failed to run (rc={proc.returncode})")
    return proc.stdout


def count_findings(output: str) -> Counter:
    counts: Counter = Counter()
    for line in output.splitlines():
        m = FINDING_RE.match(line.strip())
        if not m:
            continue
        fname = Path(m.group("path")).name
        for check in m.group("checks").split(","):
            counts[f"{fname}:{check.strip()}"] += 1
    return counts


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("sources", nargs="+")
    ap.add_argument("--update", action="store_true",
                    help="rewrite the baseline from this run's findings")
    ap.add_argument("--cc-flag", action="append", default=[],
                    help="extra compiler flag after -- (repeatable)")
    args = ap.parse_args()

    if shutil.which("clang-tidy") is None:
        raise SystemExit("clang-tidy not found on PATH")

    counts = count_findings(run_tidy(args.sources, args.cc_flag))

    if args.update:
        BASELINE.write_text(json.dumps(dict(sorted(counts.items())),
                                       indent=2) + "\n")
        print(f"baseline updated: {sum(counts.values())} finding(s) across "
              f"{len(counts)} (file, check) pairs")
        return 0

    try:
        baseline = Counter(json.loads(BASELINE.read_text()))
    except FileNotFoundError:
        baseline = Counter()

    new = {k: c - baseline.get(k, 0) for k, c in counts.items()
           if c > baseline.get(k, 0)}
    gone = {k: baseline[k] - counts.get(k, 0) for k in baseline
            if counts.get(k, 0) < baseline[k]}
    for k, c in sorted(new.items()):
        print(f"NEW: {k} (+{c})")
    for k, c in sorted(gone.items()):
        print(f"fixed vs baseline: {k} (-{c}) — consider --update to ratchet")
    total = sum(counts.values())
    print(f"clang-tidy: {total} finding(s), baseline "
          f"{sum(baseline.values())}, new {sum(new.values())}")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
