#!/usr/bin/env python
"""Critical-path report over a demodel trace JSONL — ONE JSON line.

Reads the span records ``DEMODEL_TRACE=/path`` produced (one JSON object
per finished span, the :mod:`demodel_tpu.utils.trace` contract), rebuilds
the span tree per trace, and prints:

- the **critical path** of the longest trace: walking back from the root
  span's end, the chain of child spans that actually gated completion,
  with each hop's duration and **self time** (duration not covered by its
  own critical child) — "the 30 s went: 26 s budget-wait under
  prefetch-fetch, 3 s window-read retries, 1 s place";
- a **per-stage breakdown**: count / total / max seconds per span name
  across the whole file — where wall-clock concentrates even off the
  critical path.

Same one-JSON-line contract as ``bench.py`` / ``tools/bench_serve.py`` so
drivers can scrape it. ``--chrome out.json`` additionally converts the
JSONL to Chrome trace-event format (loads in Perfetto / chrome://tracing).

Usage::

    python tools/trace_report.py /tmp/pull.jsonl
    python tools/trace_report.py /tmp/pull.jsonl --chrome /tmp/pull.json
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict
from pathlib import Path


def load_records(path: Path) -> list[dict]:
    """Parse the JSONL, skipping blank lines; malformed lines raise (the
    smoke gate's whole point is 'the file parses')."""
    records = []
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                raise SystemExit(f"{path}:{i}: bad trace line: {e}") from e
            if not isinstance(rec, dict) or "span" not in rec:
                raise SystemExit(f"{path}:{i}: not a span record")
            records.append(rec)
    return records


def _trace_of(records: list[dict], trace_id: str) -> list[dict]:
    return [r for r in records if r["trace"] == trace_id]


def _roots(spans: list[dict]) -> list[dict]:
    ids = {r["span"] for r in spans}
    return [r for r in spans if not r.get("parent") or r["parent"] not in ids]


def critical_path(spans: list[dict], root: dict) -> list[dict]:
    """The chain of spans that gated ``root``'s completion.

    Walk back from the root's end: the critical child is the one whose
    END is latest (but not past the cursor); recurse into it, move the
    cursor to its start, repeat among its earlier siblings. Each hop
    reports ``secs`` (span duration) and ``self_secs`` (duration minus
    the part covered by its own critical child) — self time is where the
    wait actually happened."""
    children: dict[str, list[dict]] = defaultdict(list)
    for r in spans:
        if r.get("parent"):
            children[r["parent"]].append(r)

    def end(r: dict) -> float:
        return r["ts"] + r.get("dur", 0.0)

    path: list[dict] = []

    def walk(span: dict) -> float:
        """Append span, recurse into its critical child; returns the
        span's self time."""
        kids = [k for k in children.get(span["span"], ())
                if end(k) <= end(span) + 1e-9]
        covered = 0.0
        cursor = end(span)
        # repeatedly take the child gating `cursor`, then continue among
        # children that finished before it started. Each child is
        # consumed at most once: a zero-duration span whose end equals
        # the cursor would otherwise be re-selected forever.
        chain = []
        remaining = list(kids)
        while True:
            cands = [k for k in remaining if end(k) <= cursor + 1e-9]
            if not cands:
                break
            nxt = max(cands, key=end)
            remaining.remove(nxt)
            chain.append(nxt)
            covered += nxt.get("dur", 0.0)
            cursor = nxt["ts"]
        entry = {
            "name": span["name"],
            "secs": round(span.get("dur", 0.0), 6),
            "self_secs": round(max(0.0, span.get("dur", 0.0) - covered), 6),
        }
        if span.get("status") == "error":
            entry["error"] = span.get("error", "")
        path.append(entry)
        # only the GATING child (latest end) continues the critical path;
        # earlier chain entries were concurrent cover, already accounted
        if chain:
            walk(chain[0])
        return entry["self_secs"]

    walk(root)
    return path


def stage_breakdown(records: list[dict]) -> dict:
    stages: dict[str, dict] = {}
    for r in records:
        s = stages.setdefault(r["name"], {"count": 0, "total_secs": 0.0,
                                          "max_secs": 0.0, "errors": 0})
        d = r.get("dur", 0.0)
        s["count"] += 1
        s["total_secs"] += d
        s["max_secs"] = max(s["max_secs"], d)
        if r.get("status") == "error":
            s["errors"] += 1
    for s in stages.values():
        s["total_secs"] = round(s["total_secs"], 6)
        s["max_secs"] = round(s["max_secs"], 6)
    return dict(sorted(stages.items(),
                       key=lambda kv: -kv[1]["total_secs"]))


def report(records: list[dict]) -> dict:
    by_trace: dict[str, list[dict]] = defaultdict(list)
    for r in records:
        by_trace[r["trace"]].append(r)
    # the headline trace: the one whose root span ran longest
    best_root, best_trace = None, None
    for tid, spans in by_trace.items():
        for root in _roots(spans):
            if best_root is None or root.get("dur", 0.0) > best_root.get(
                    "dur", 0.0):
                best_root, best_trace = root, tid
    out = {
        "metric": "trace_report",
        "traces": len(by_trace),
        "spans": len(records),
        "events": sum(len(r.get("events", ())) for r in records),
        "stages": stage_breakdown(records),
    }
    if best_root is not None and best_trace is not None:
        out["trace"] = best_trace
        out["wall_secs"] = round(best_root.get("dur", 0.0), 6)
        out["critical_path"] = critical_path(by_trace[best_trace],
                                             best_root)
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("jsonl", type=Path, help="trace JSONL (DEMODEL_TRACE)")
    ap.add_argument("--chrome", type=Path, default=None,
                    help="also write Chrome trace-event JSON here "
                         "(open in Perfetto / chrome://tracing)")
    args = ap.parse_args(argv)

    records = load_records(args.jsonl)
    if not records:
        raise SystemExit(f"{args.jsonl}: no span records")
    out = report(records)
    if args.chrome is not None:
        sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
        from demodel_tpu.utils.trace import chrome_events

        events = chrome_events(records)
        args.chrome.write_text(json.dumps(
            {"traceEvents": events, "displayTimeUnit": "ms"}))
        out["chrome_events"] = len(events)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
