"""Dated TPU-tunnel probe (VERDICT r3 #1 outage fallback).

Appends one JSON line per run to TUNNEL_LOG.jsonl: timestamp, whether the
axon-tunnelled chip answered within the deadline, backend-init time, and a
small+large `device_put` throughput sample. Run it in a killable child —
the known failure mode is an uninterruptible hang inside
``make_c_api_client`` (PROFILE_r03.md), so the parent enforces the timeout.

Usage: python tools/tunnel_probe.py [--timeout 240]
"""

from __future__ import annotations

import datetime
import json
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
LOG = REPO / "TUNNEL_LOG.jsonl"


def _child() -> None:
    t0 = time.time()
    import jax
    import numpy as np

    d = jax.devices()[0]
    init_s = round(time.time() - t0, 1)
    x = np.zeros(1 << 18, np.float32)  # 1 MB
    t = time.time()
    jax.block_until_ready(jax.device_put(x, d))
    small_s = round(time.time() - t, 2)
    big = np.zeros(16 << 20 >> 2, np.float32)  # 16 MB
    t = time.time()
    jax.block_until_ready(jax.device_put(big, d))
    big_dt = time.time() - t
    print(json.dumps({
        "ok": True, "device": str(d), "init_s": init_s,
        "put_1mb_s": small_s,
        "put_16mb_mbps": round(16 / big_dt, 1),
    }))


def main() -> None:
    if "--child" in sys.argv:
        _child()
        return
    timeout = 240
    if "--timeout" in sys.argv:
        timeout = int(sys.argv[sys.argv.index("--timeout") + 1])
    stamp = datetime.datetime.now(datetime.timezone.utc).isoformat(
        timespec="seconds")
    rec: dict = {"ts": stamp, "timeout_s": timeout}
    try:
        proc = subprocess.run(
            [sys.executable, __file__, "--child"],
            capture_output=True, text=True, timeout=timeout,
        )
        parsed = None
        for line in reversed(proc.stdout.strip().splitlines()):
            try:
                parsed = json.loads(line)
                break
            except ValueError:
                continue
        if parsed:
            rec.update(parsed)
        else:
            rec.update({"ok": False,
                        "error": (proc.stderr or "no output")[-400:]})
    except subprocess.TimeoutExpired:
        rec.update({"ok": False, "error": f"wedged: no response in {timeout}s"})
    with open(LOG, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
