"""Round-5 tunnel watcher: probe every PERIOD seconds; on the first
ok:true probe, fire tools/on_recovery.py (bench + flash on-chip check +
spaced reps) exactly once, then keep probing so the log keeps recording
channel health.

Runs detached for the whole round; state (whether recovery fired) is a
marker file so a restarted watcher does not re-fire.
"""

from __future__ import annotations

import json
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
MARKER = REPO / ".recovery_fired_r05"
# windows have died at ~45 min and a wedged probe burns its 180 s
# timeout anyway — a 300 s sleep gives ~8 min discovery latency
# (vs ~13 min at 600 s), recovering ~10% of a typical window
PERIOD = 300


def probe_once(timeout: int = 180) -> dict:
    # tunnel_probe.py itself enforces `timeout` on its child; the outer
    # margin only guards against the parent probe process wedging too —
    # and a TimeoutExpired here must NOT kill the watcher (the known
    # failure mode is exactly long strings of wedged probes)
    try:
        r = subprocess.run(
            [sys.executable, str(REPO / "tools/tunnel_probe.py"),
             "--timeout", str(timeout)],
            capture_output=True, text=True, timeout=timeout + 60)
    except subprocess.TimeoutExpired:
        return {"ok": False, "error": "probe wrapper wedged"}
    try:
        return json.loads(r.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {"ok": False, "error": "probe produced no JSON"}


def main() -> None:
    while True:
        rec = probe_once()
        if rec.get("ok") and not MARKER.exists():
            MARKER.write_text(json.dumps(rec))
            print("[watch] tunnel alive — firing recovery", file=sys.stderr)
            try:
                subprocess.run(
                    [sys.executable, str(REPO / "tools/on_recovery.py")],
                    timeout=7200)
            except subprocess.TimeoutExpired:
                print("[watch] recovery run wedged; watcher continues",
                      file=sys.stderr)
        time.sleep(PERIOD)


if __name__ == "__main__":
    main()
